"""Continuous (in-flight) batching scheduler over the paged KV pool.

Reference role: iteration-level scheduling from Orca (Yu et al., OSDI '22)
plus the chunked-prefill/decode interleaving of Sarathi-Serve (Agrawal et
al., OSDI '24), on the substrate PRs 1-3 built: block tables + atomic
reserve (kv_cache.py), deadline/shed/CAS semantics (resilience.py,
serving.py) and request-scoped tracing (observability/trace.py).

Shape of the thing — the fixed-batch `GenerateBatchingPredictor` runs one
compiled program per whole batch: a request arriving mid-cycle waits for the
next batch, a long prompt stalls every decoder batched with it, and a batch
is only as fast as its slowest member. `ContinuousGenerateBatchingPredictor`
replaces the per-batch launch with a persistent TICK loop over a fixed set
of S slots:

* admit  — each tick, queued requests take free slots by atomically
  reserving their blocks from the shared pool; a dry pool defers or sheds
  THAT request only (PR 2 semantics, `CacheOutOfBlocks` never touches
  batchmates).
* prefill — prompts are split into fixed-width chunks; each tick spends at
  most `prefill_token_budget` prompt tokens (across slots) in ONE
  `prefill_chunk` launch, so a 10k-token prompt never stalls in-flight
  decoders for more than a chunk's worth of compute (this is what bounds
  decode p99 — docs/PERF.md).
* decode — all decoding slots advance `decode_steps` tokens in ONE
  `decode_step` launch (a compiled scan: the host syncs per tick, not per
  token).
* retire — finished / EOS / deadline-expired / client-cancelled sequences
  free their blocks and slot at the next tick boundary; the freed slot is
  admissible on the same tick.

Both step programs are FIXED WIDTH (S slots, static chunk width, static
table width, per-slot active masks), so the scheduler runs exactly two
compiled programs forever — no shape-driven recompiles as sequences come
and go (the `recompile-hazard` lint rule gates this by construction;
analysis/zoo.py registers both programs). With ``spec_k > 0`` the decode
tick is replaced by the equally fixed-width speculative ``verify_step``
program (ISSUE-10): up to spec_k host-drafted tokens per slot are scored
in one prefill-shaped launch and accepted/rejected in-program, emitting
1 + accepted tokens per slot per tick with the output distribution
provably unchanged — still exactly two programs, still zero recompiles
across accept/reject/admit/retire patterns.

Everything the fixed-batch predictor guaranteed still holds per token-step:
one Deadline rides HTTP -> queue -> slot and expiry anywhere reaches exactly
ONE terminal outcome through the request CAS; a dying batcher thread
releases every slot's blocks and re-enqueues still-pending sequences before
the supervisor heals it; `close()` fails in-flight sequences with
ServiceUnavailable instead of stranding clients.
"""
from __future__ import annotations

import collections
import itertools
import queue
import threading

import numpy as np

from ..analysis.lockwitness import make_lock
from .faults import ThreadDeath
from .kv_cache import CacheOutOfBlocks
from .resilience import DeadlineExceeded, ServiceUnavailable
from .serving import _PENDING, GenerateBatchingPredictor
from .speculative import make_drafter
from .warmup import AOTWarmup
from .warmup import notify as _recompile_notify

__all__ = ["ContinuousGenerateBatchingPredictor", "phase_walls",
           "attribution_shares"]

_PREFILL, _DECODE = "prefill", "decode"


def phase_walls(t0, t_admit, t_first, t_end, paused_s, paused_pre_s):
    """Decompose one request's wall time into phase walls (seconds).

    Pure function over the scheduler-clock stamps (ISSUE-18): acceptance
    (t0), slot admission (t_admit), first generated token (t_first, None if
    the request never produced one), terminal (t_end), plus total paused
    seconds and the portion paused before the first token. Returns
    (queue_s, prefill_s, paused_s, decode_s), each clamped >= 0:

    * queue   — acceptance to slot admission (never admitted: the whole
      life was queue wait).
    * prefill — admission to first token, minus pre-first-token pause time
      (no first token: everything after admission that wasn't a pause).
    * paused  — preemption park time, charged to its OWN phase: a paused
      sequence is neither prefilling nor decoding, and folding it into
      either would misattribute a scheduling decision as model latency.
    * decode  — first token to terminal, minus post-first-token pauses.
    """
    if t0 is None:
        return (0.0, 0.0, 0.0, 0.0)
    if t_admit is None:
        return (max(0.0, t_end - t0), 0.0, 0.0, 0.0)
    queue_s = max(0.0, t_admit - t0)
    paused_total = max(0.0, float(paused_s))
    paused_pre = min(paused_total, max(0.0, float(paused_pre_s)))
    if t_first is None:
        prefill_s = max(0.0, (t_end - t_admit) - paused_total)
        return (queue_s, prefill_s, paused_total, 0.0)
    prefill_s = max(0.0, (t_first - t_admit) - paused_pre)
    decode_s = max(0.0, (t_end - t_first) - (paused_total - paused_pre))
    return (queue_s, prefill_s, paused_total, decode_s)


def attribution_shares(queue_s, prefill_s, paused_s, decode_s):
    """Phase walls -> the terminal span's deadline-attribution tags.

    Shares are normalized by the walls' own sum so they add to 1.0 by
    construction (the property test's invariant); a zero-duration request
    (door rejection, instant shed) is all queue — the phase it died in."""
    total = queue_s + prefill_s + paused_s + decode_s
    if total <= 0.0:
        return {"queue_share": 1.0, "prefill_share": 0.0,
                "paused_share": 0.0, "decode_share": 0.0}
    return {"queue_share": round(queue_s / total, 6),
            "prefill_share": round(prefill_s / total, 6),
            "paused_share": round(paused_s / total, 6),
            "decode_share": round(decode_s / total, 6)}


class _SlotSeq:
    """One in-flight sequence bound to a scheduler slot."""

    __slots__ = ("req", "rid", "ids", "out_dtype", "plen", "pos", "tok",
                 "length", "generated", "table", "phase", "max_new", "order",
                 "temperature", "top_k", "spec", "prefix_hit", "digests",
                 "flushed", "adapter", "adapter_seed", "tenant", "priority",
                 "qos_held", "t_admit", "t_first", "t_last", "t_pause",
                 "paused_s", "paused_pre_s", "n_tok")

    def __init__(self, req, rid, ids, out_dtype, max_new, order):
        self.req = req
        self.rid = rid
        self.ids = ids              # int64 prompt (program input dtype)
        self.out_dtype = out_dtype  # client dtype, restored on finish
        self.plen = len(ids)
        self.pos = 0                # prefill progress (tokens in the cache)
        self.tok = 0                # next decode input (last sampled token)
        self.length = 0             # cache rows present
        self.generated: list[int] = []
        self.table = None           # np.int32 [table_width] page ids
        self.phase = _PREFILL
        self.max_new = int(max_new)
        self.order = order          # admit sequence number (FIFO fairness)
        # per-request sampling params: traced [S]-array inputs of the step
        # programs, so mixed-sampler slots share one compiled program
        self.temperature = float(req.temperature or 0.0)
        self.top_k = int(req.top_k or 0)
        # per-request speculation opt-out (X-Spec header); honored only
        # when the scheduler runs with spec_k > 0 — an opted-out slot rides
        # the same verify program with draft_len 0 (no recompile)
        self.spec = True if getattr(req, "spec", None) is None else bool(
            req.spec)
        # prefix-cache state (ISSUE-11): tokens satisfied from shared blocks
        # at admission, the prompt's full-block digest chain (for indexing
        # at prefill commit), and the streamed-token high-water mark
        self.prefix_hit = 0
        self.digests = None
        self.flushed = 0
        # per-request model delta (ISSUE-15): the adapter's bank row (0 =
        # base/identity) — a traced [S] step-program input, so heterogeneous
        # adapter mixes share one compiled program — and its registration
        # uid, which seeds the prefix-cache digest chain (KV isolation)
        self.adapter = 0
        self.adapter_seed = b""
        # multi-tenant QoS (ISSUE-17): resolved tenant name + priority tier
        # (lower = more urgent), and whether this sequence currently holds
        # its tenant's fair-share inflight count (pause releases it while
        # the blocks stay reserved)
        self.tenant = None
        self.priority = 0
        self.qos_held = False
        # phase attribution (ISSUE-18): scheduler-clock stamps — admission,
        # first/last generated token — plus paused-time accounting (total
        # seconds parked, the portion parked before the first token, and
        # the open pause interval's start). Pause time charges a distinct
        # `paused` phase: it is in neither TTFT's prefill nor TPOT's decode.
        self.t_admit = None
        self.t_first = None
        self.t_last = None
        self.t_pause = None
        self.paused_s = 0.0
        self.paused_pre_s = 0.0
        self.n_tok = 0      # tokens actually sampled (EOS freeze excluded)


class ContinuousGenerateBatchingPredictor(GenerateBatchingPredictor):
    """Token-level (continuous) scheduler for /generate over the paged pool.

    Knobs (see docs/DEPLOYMENT.md "Continuous batching"):

    max_slots            decode width S: concurrent in-flight sequences.
    prefill_chunk        static chunk width C — one slot's prefill quantum.
    prefill_token_budget max prompt tokens spent per tick across all slots
                         (default 2*C). Lower bounds decode latency under
                         long-prompt pressure; higher finishes prompts
                         sooner.
    decode_steps         tokens each decoding slot advances per tick (one
                         compiled scan). Higher amortizes dispatch; lower
                         tightens admit/retire granularity.
    max_seq_len          static per-sequence capacity (prompt + new tokens);
                         sets the block-table width of the two compiled
                         programs. Default: the whole pool for one sequence
                         (correct but widest table; size it to your real
                         longest request).
    max_new_tokens       server-wide output cap; `infer(max_new_tokens=n)`
                         requests fewer — the sequence retires at n and its
                         slot is reused immediately (the fixed-batch path
                         has no equivalent: every batch member decodes the
                         full cap).
    eos_token_id         optional early-exit token; on EOS the remainder is
                         frozen to EOS (sampler parity) and the slot retires.
    spec_k               speculative decoding width (ISSUE-10): when > 0 the
                         decode tick becomes one fixed-width `verify_step`
                         launch scoring up to spec_k host-drafted tokens per
                         slot — 1 + accepted tokens per launch, output
                         distribution unchanged. 0 (default) keeps the plain
                         decode_step tick.
    drafter              'ngram' (default; prompt-lookup, host-free) |
                         'self' (shallow-window reuse of the target model) |
                         any inference.speculative.Drafter instance.
    prefix_cache         content-addressed KV block sharing (ISSUE-11):
                         True builds a `PrefixCache` over this scheduler's
                         pool (pass an instance to share one across
                         predictors on the SAME pool). Admission consults
                         the index and a hit skips chunked prefill straight
                         to the first novel token — prefill cost ~O(new
                         tokens) on overlapping traffic, token-identical
                         output (greedy, sampled, and speculative paths).
                         Default False: the pool behaves exactly as before.
    admit_policy         'fifo' (default) | 'shortest_prompt_first': free
                         slots take the queued request with the shortest
                         prompt (ties to the most urgent deadline, then
                         arrival) — shorter prompts prefill in fewer chunks,
                         so slot turnover and aggregate goodput rise under
                         mixed-length pressure at the cost of bounded
                         long-prompt delay (they still admit whenever they
                         are the backlog minimum).
    warmup               ISSUE-13: True compiles every step program of this
                         configuration's compile-surface manifest
                         (analysis/compilesurface.py) on a background
                         "aot-warmup" thread before `ready()` reports True —
                         /readyz stays 503 until the first request can run
                         without a cold build. Once warmup covers the
                         manifest, the post-ready compile SENTINEL arms: any
                         later cold build increments
                         `paddle_serving_recompiles_total{component,program}`
                         and notifies the chaos-suite witness
                         (inference/warmup.py). Default False: ready
                         immediately, programs build lazily, sentinel off.
    compile_cache_dir    optional persistent XLA compile-cache directory
                         (warmup runs point the process at it); a restarted
                         process reuses the serialized executables and pays
                         trace time only — the docs/DEPLOYMENT.md cold-start
                         runbook knob. Meaningful with warmup=True.
    hbm_budget           ISSUE-14: per-chip HBM budget in bytes. When set
                         (and no explicit kv_cache/num_blocks), the pool is
                         sized FROM the residency plan — analysis/hbm.py
                         ``plan_kv_pool`` takes what fits the budget after
                         params + headroom, clamped to what max_slots x
                         max_seq_len requests can actually reach — and the
                         plan publishes ``paddle_hbm_planned_bytes{
                         component=params|kv_pool|prefix_tier|temps|
                         adapter_bank}`` next
                         to ``paddle_hbm_budget_bytes``. ValueError when the
                         budget cannot fit even one sequence's blocks.
                         Default None: num_blocks is taken as given.
    adapters             ISSUE-15: an `inference.adapters.AdapterRegistry`
                         over THIS model — multi-LoRA serving. Every step
                         launch grows a traced [S] bank-index input;
                         `infer(adapter=name)` (HTTP `X-Adapter`) routes a
                         request through its adapter's low-rank delta while
                         base requests ride bank slot 0 (identity) of the
                         SAME program. Load/unload/mix changes never
                         recompile; admission refcount-pins the slot so an
                         unload can't race in-flight traffic. Default None:
                         base model only, step programs keep their exact
                         pre-adapter signature.
    qos                  ISSUE-17: an `inference.qos.TenantLedger` — multi-
                         tenant weighted fair-share admission, per-tenant
                         token-budget rate limits (429 + computed
                         Retry-After at the admission door) and priority
                         preemption: a strictly more urgent waiting request
                         PAUSES the least urgent running sequence (blocks
                         retained, slot state parked, tick width freed) and
                         the paused sequence resumes bit-exactly later
                         through the same continuation bookkeeping a
                         prefix-cache hit uses. Pause/resume and tenant mix
                         are host-side only: ZERO new compiled programs.
                         Share ONE ledger across a fleet's replicas for
                         global buckets. Default None: untenanted traffic,
                         admission exactly as before.
    slo                  ISSUE-18: an `observability.slo.SLOMonitor` —
                         retirement feeds it per-tenant TTFT/TPOT samples
                         and every terminal CAS feeds availability
                         (good = the outcome's HTTP status < 500), and it
                         exports `paddle_slo_error_budget_remaining{slo}` /
                         `paddle_slo_burn_rate{slo,window}` on this
                         scheduler's registry. With a flight recorder also
                         installed, a policy's not-alerting -> alerting
                         edge triggers an automatic ring dump (the breach
                         ships its own postmortem). Default None: no SLO
                         series (gauges exist iff a policy is installed).
    flight_recorder      ISSUE-18: per-tick postmortem ring. True builds a
                         default `observability.flightrecorder.
                         FlightRecorder`; an int sets its capacity; pass an
                         instance to share/configure. Each tick appends a
                         snapshot (slot map with tenant/adapter/phase,
                         batch widths, KV block accounting, paused/pending
                         depths, ledger fair-ratios) — dumped on demand
                         (`/debug/ticks`), on SLO alert, and by the chaos
                         conftest fixture on test failure. Overhead is
                         bench-gated <= 5% (slo_observability leg). Default
                         False: no capture, tick loop byte-identical.
    utilization          ISSUE-19: per-tick FLOPs attribution. True builds
                         a default `observability.utilization.
                         UtilizationLedger`; pass an instance to configure
                         (injected clock / peak_flops). Every tick's
                         issued step-program FLOPs (cost_flops on the
                         lowered runner, one trace per program key) split
                         into useful / pad / spec_waste with EXACT integer
                         conservation, useful FLOPs bill per tenant
                         (paused time never bills — preempted sequences
                         are off-slot), and tick wall splits into launch
                         vs host gap. Exports `paddle_serving_flops_total{
                         kind}`, `paddle_tenant_flops_total{tenant}`,
                         `paddle_serving_host_gap_seconds` and (with a
                         known device peak) `paddle_serving_mfu`; JSON at
                         `/utilization`, per-tick fields on the flight
                         ring. Overhead is bench-gated <= 5%
                         (serving_utilization leg) with zero new compiled
                         programs. Default False: no attribution, launches
                         carry no flops probe.
    """

    _component = "continuous"
    supports_sampler_knobs = True   # serving.py gates per-request headers
    supports_streaming = True       # tick-boundary flushes -> infer_stream

    @property
    def supports_adapters(self):
        """X-Adapter gate (serving.py): routing needs an actual registry —
        a continuous scheduler without one 400s the header like any
        whole-batch predictor would."""
        return getattr(self, "adapters", None) is not None

    @property
    def supports_tenants(self):
        """X-Tenant gate (serving.py): tenant routing needs a TenantLedger
        (qos= knob) — same strict 400 taxonomy as X-Adapter."""
        return getattr(self, "qos", None) is not None

    def __init__(self, model, max_slots=8, prefill_chunk=16,
                 prefill_token_budget=None, decode_steps=4, max_seq_len=None,
                 eos_token_id=None, max_defers=32, spec_k=0, drafter="ngram",
                 admit_policy="fifo", prefix_cache=False, warmup=False,
                 compile_cache_dir=None, hbm_budget=None, adapters=None,
                 qos=None, slo=None, flight_recorder=False,
                 utilization=False, **kwargs):
        self.max_slots = int(max_slots)
        self.prefill_chunk = int(prefill_chunk)
        self.prefill_token_budget = int(prefill_token_budget
                                        if prefill_token_budget is not None
                                        else 2 * self.prefill_chunk)
        if self.prefill_token_budget < self.prefill_chunk:
            raise ValueError("prefill_token_budget must cover at least one "
                             "chunk")
        self.decode_steps = int(decode_steps)
        self.eos_token_id = (None if eos_token_id is None
                             else int(eos_token_id))
        self.spec_k = int(spec_k)
        if self.spec_k < 0:
            raise ValueError("spec_k must be >= 0")
        self._drafter = (make_drafter(drafter, model) if self.spec_k > 0
                         else None)
        if admit_policy not in ("fifo", "shortest_prompt_first"):
            raise ValueError(f"unknown admit_policy {admit_policy!r} "
                             "(fifo | shortest_prompt_first)")
        self.admit_policy = admit_policy
        # reorder buffer for non-FIFO admission; deque: appends/pops are
        # atomic under the GIL (thread-lint atomic-type contract) — touched
        # by the batcher thread and by close()
        self._backlog: collections.deque = collections.deque()
        # speculation accounting (host ints; written under _slot_lock, read
        # by registry gauge scrapes from other threads)
        self._spec_drafted = 0
        self._spec_accepted = 0
        # per-tick RNG seed draw (atomic): sampling slots get fresh noise
        # each tick; greedy output is seed-independent (argmax)
        self._seed = itertools.count(1)
        # slot state exists BEFORE super().__init__ starts the loop thread
        # (prefix attrs too: the tick loop reads them; the real PrefixCache
        # is published below, after super() builds the kv pool — a tick
        # that races attachment just serves its admissions cold)
        self.prefix_cache = None
        self._prefix_hit_counter = None
        # AOT warmup state exists BEFORE super().__init__ too: the tick
        # loop's ready-gate preamble reads these from the batcher thread.
        # Events/deques only (thread-lint atomic-type contract) — the warm
        # thread writes, the batcher/readyz/test threads read.
        self.warmup = bool(warmup)
        self.compile_cache_dir = compile_cache_dir
        self._warm_done = threading.Event()
        self._warm_armed = threading.Event()
        self._warm_stats: collections.deque = collections.deque(maxlen=8)
        self._warm_errors: collections.deque = collections.deque(maxlen=8)
        self._warm_thread = None
        self._recompile_counter = None
        self._slots: list = [None] * self.max_slots
        # multi-LoRA registry (ISSUE-15): published before super().__init__
        # starts the tick thread — ticks read it, admission pins slots in it
        self.adapters = adapters
        self._lora_requests_counter = None
        # multi-tenant QoS ledger (ISSUE-17): published before the tick
        # thread starts; _qos_admit reads it. Paused (preempted) sequences
        # park in a deque (documented-atomic type): appended/removed by the
        # batcher thread, scraped by gauges and pending() from others.
        self.qos = qos
        self._paused: collections.deque = collections.deque()
        # ISSUE-18 SLO monitor + flight recorder: published before the tick
        # thread starts (the tick loop's retirement paths and _flight_tick
        # read them); the histograms/gauges bind after super() like every
        # other metric family — no request can be in flight until __init__
        # returns, so the late bind is unobservable
        self.slo = slo
        if flight_recorder is False or flight_recorder is None:
            self.flight = None
        elif flight_recorder is True:
            from ..observability.flightrecorder import FlightRecorder
            self.flight = FlightRecorder()
        elif isinstance(flight_recorder, int):
            from ..observability.flightrecorder import FlightRecorder
            self.flight = FlightRecorder(capacity=flight_recorder)
        else:
            self.flight = flight_recorder
        # ISSUE-19 utilization ledger: published before the tick thread
        # starts (tick fns and _flight_tick read it). The timing hook grows
        # a wants_flops marker ONLY when a ledger is installed — that is
        # what gates the one-trace-per-program flops probe in generation.py,
        # so a bare scheduler's launch path is byte-identical.
        if utilization is False or utilization is None:
            self.util = None
        elif utilization is True:
            from ..observability.utilization import UtilizationLedger
            self.util = UtilizationLedger()
        else:
            self.util = utilization
        self._last_launch = None        # tick-thread-only hook stash
        hook = self._gen_timing
        if self.util is not None:
            def hook(info, _h=self._gen_timing):
                _h(info)
            hook.wants_flops = True
        self._timing_hook = hook
        self._ttft_hist = None
        self._tpot_hist = None
        # gauges scrape from other threads; witness-wrapped under chaos
        self._slot_lock = make_lock(
            "scheduler.ContinuousGenerateBatchingPredictor._slot_lock")
        self.max_seq_len = None             # finalized below (needs kv_cache)
        self.table_width = None
        # ISSUE-14: hbm_budget= sizes the pool FROM the residency plan
        # (analysis/hbm.py plan_kv_pool) instead of taking num_blocks on
        # faith — the static lint and the runtime share one arithmetic.
        self.hbm_budget = None if hbm_budget is None else int(hbm_budget)
        self._hbm_plan = None
        if (self.hbm_budget is not None and kwargs.get("kv_cache") is None
                and "num_blocks" not in kwargs):
            from ..analysis.hbm import params_bytes_of, plan_kv_pool

            layers, kv_h, hd = (int(x) for x in model._decode_cache_spec())
            sizing = plan_kv_pool(
                self.hbm_budget, num_layers=layers, num_kv_heads=kv_h,
                head_dim=hd, block_size=kwargs.get("block_size", 32),
                slots=self.max_slots, max_seq_len=max_seq_len,
                params_bytes=params_bytes_of(model),
                name=self._component, prefill_chunk=self.prefill_chunk,
                decode_steps=self.decode_steps, spec_k=self.spec_k,
                eos_token_id=self.eos_token_id,
                adapter_bank_bytes=(0 if adapters is None
                                    else adapters.bank_bytes()))
            kwargs["num_blocks"] = sizing["num_blocks"]
            self._hbm_plan = sizing["plan"]
        super().__init__(model, max_batch_size=max_slots,
                         max_defers=max_defers, **kwargs)
        pool_tokens = self.kv_cache.num_blocks * self.kv_cache.block_size
        self.max_seq_len = int(max_seq_len) if max_seq_len else pool_tokens
        if self.max_seq_len > pool_tokens:
            raise ValueError(f"max_seq_len {self.max_seq_len} exceeds the "
                             f"pool ({pool_tokens} tokens)")
        self.table_width = self.kv_cache.blocks_for(self.max_seq_len)
        (self._spec_counter, self._lora_requests_counter,
         self._ttft_hist, self._tpot_hist) = self._bind_scheduler_metrics()
        if prefix_cache:
            from .prefix_cache import PrefixCache
            pc = (prefix_cache if isinstance(prefix_cache, PrefixCache)
                  else PrefixCache(self.kv_cache, faults=self._faults))
            pc.bind_metrics(self.metrics.registry, component=self._component)
            self._prefix_hit_counter = self.metrics.registry.counter(
                "paddle_prefix_hit_tokens_total",
                "Prompt tokens served from shared prefix blocks instead of "
                "prefill compute", labels=("component",)).labels(
                    self._component)
            self.prefix_cache = pc      # published last: counter is ready
        # ISSUE-13 post-ready compile sentinel: counter exists before the
        # warm thread can arm it (the only reader of _recompile_counter is
        # the armed branch of _gen_timing, and arming happens on this thread)
        self._recompile_counter = self.metrics.registry.counter(
            "paddle_serving_recompiles_total",
            "Post-ready step-program cold builds by program — stays 0 when "
            "the AOT warmup covered the compile-surface manifest "
            "(analysis/compilesurface.py)", labels=("component", "program"))
        if self.warmup and not self.fallback_dense:
            self._warm_thread = threading.Thread(
                target=self._warm_start, name="aot-warmup", daemon=True)
            self._warm_thread.start()
        else:
            # nothing to compile ahead of time (or the dense fallback path
            # owns its own cache): ready immediately, sentinel stays off
            self._warm_done.set()

    # ------------------------------------------------------------ AOT warmup
    def _warm_start(self):
        """Body of the aot-warmup thread: compile the manifest, then gate.

        A warmup FAILURE never wedges readiness — the predictor serves cold
        exactly as if warmup were off, with the error recorded in
        warm_errors() and the sentinel left unarmed (a cold build after a
        failed warmup is expected, not a violation)."""
        try:
            stats = AOTWarmup(self, cache_dir=self.compile_cache_dir,
                              tracer=self.tracer).run()
            self._warm_stats.append(stats)
            if not stats["missing"] and not self._stop.is_set():
                self._warm_armed.set()
        except Exception as e:            # noqa: BLE001 — recorded, not fatal
            self._warm_errors.append(e)
        finally:
            self._warm_done.set()

    def warm_stats(self):
        """Latest AOT warmup stats dict (programs/compiled/missing/
        fingerprints/seconds), or None before the first run finishes."""
        return self._warm_stats[-1] if self._warm_stats else None

    def warm_errors(self):
        return list(self._warm_errors)

    def ready(self) -> bool:
        """/readyz gate (ISSUE-13): False until the AOT warmup finished
        (instantly true with warmup=False) and while shutting down. The
        fleet router skips not-ready replicas (`ReplicaFleet._pick`), so a
        warming replica joins rotation only once its programs are built."""
        return self._warm_done.is_set() and not self._stop.is_set()

    # ------------------------------------------------------------- telemetry
    def _bind_scheduler_metrics(self):
        reg = self.metrics.registry
        slots = reg.gauge(
            "paddle_sched_slots",
            "Continuous-scheduler slots by phase; "
            "prefill + decode + free == slot count",
            labels=("component", "phase"))
        slots.labels(self._component, _PREFILL).set_function(
            lambda: self._phase_count(_PREFILL))
        slots.labels(self._component, _DECODE).set_function(
            lambda: self._phase_count(_DECODE))
        slots.labels(self._component, "free").set_function(
            lambda: self.max_slots - self._phase_count(None))
        reg.gauge(
            "paddle_sched_slot_count", "Configured continuous-scheduler "
            "slot width S", labels=("component",)).labels(
                self._component).set_function(lambda: self.max_slots)
        reg.gauge(
            "paddle_sched_prefill_token_budget",
            "Max prompt tokens spent per tick across slots (chunked "
            "prefill knob)", labels=("component",)).labels(
                self._component).set_function(
                    lambda: self.prefill_token_budget)
        reg.gauge(
            "paddle_sched_prefill_backlog_tokens",
            "Prompt tokens still to prefill across in-flight slots",
            labels=("component",)).labels(self._component).set_function(
                self._prefill_backlog)
        # speculative decoding accounting (ISSUE-10): drafted / accepted /
        # wasted token counters plus the derived acceptance-rate gauge —
        # THE dial that says whether spec_k is paying for its verify width.
        # Returned (not self-assigned) so the _spec_counter attribute write
        # happens in __init__, before any worker thread can observe it.
        # ISSUE-14 residency gauges: the plan the hbm_budget= knob sized the
        # pool from, component-by-component, next to the declared budget —
        # a scrape shows plan vs actual (paddle_kv_pool_per_chip_bytes is
        # the pool's own ground truth to reconcile against). Absent when the
        # knob is off: a gauge that would always read 0 is noise.
        if self._hbm_plan is not None:
            reg.gauge(
                "paddle_hbm_budget_bytes",
                "Declared per-chip HBM budget the serving plan was sized "
                "against (scheduler hbm_budget= knob)",
                labels=("component",)).labels(self._component).set(
                    self.hbm_budget)
            planned = reg.gauge(
                "paddle_hbm_planned_bytes",
                "Planned per-chip HBM residency by plan component "
                "(analysis/hbm.py DeploymentPlan)", labels=("component",))
            for part, nbytes in self._hbm_plan.components().items():
                planned.labels(part).set(nbytes)
        # ISSUE-15 multi-LoRA telemetry: bank occupancy by state (loaded =
        # resident, pinned = refcounted by in-flight slots, free = open
        # rows) plus per-adapter admission counts. Absent without a
        # registry — same no-dead-gauges policy as the hbm block above.
        # Returned (like spec_counter) so the attribute write lands in
        # __init__, before any worker thread can observe it.
        lora_counter = None
        if self.adapters is not None:
            lora = reg.gauge(
                "paddle_lora_adapters",
                "Adapter bank slots by state (loaded|pinned|free); slot 0 "
                "(base identity) is not counted",
                labels=("component", "state"))
            for state in ("loaded", "pinned", "free"):
                lora.labels(self._component, state).set_function(
                    lambda st=state: self.adapters.stats()[st])
            lora_counter = reg.counter(
                "paddle_lora_requests_total",
                "Admitted sequences by adapter name ('base' = no adapter)",
                labels=("component", "adapter"))
        # ISSUE-17 multi-tenant QoS telemetry: the ledger's tenant series
        # (requests/tokens/rate-limited/inflight — bound ONCE per registry,
        # fleet replicas sharing a ledger are no-ops) plus this scheduler's
        # own paused-width gauge and per-tenant backlog (scrape-time queue
        # scan: no incremental counters to drift across defer/requeue).
        if self.qos is not None:
            self.qos.bind_metrics(reg)
            reg.gauge(
                "paddle_sched_paused",
                "Preempted sequences parked off-slot (blocks retained; "
                "resumed through the prefix-hit continuation path)",
                labels=("component",)).labels(self._component).set_function(
                    lambda: float(len(self._paused)))
            backlog = reg.gauge(
                "paddle_tenant_backlog",
                "Queued (not yet slotted) requests by tenant on this "
                "scheduler (autoscaler pressure signal)",
                labels=("component", "tenant"))
            for name in self.qos.tenant_names():
                backlog.labels(self._component, name).set_function(
                    lambda n=name: float(self.tenant_backlog().get(n, 0)))
        spec_counter = reg.counter(
            "paddle_spec_tokens_total",
            "Speculative decoding tokens by kind: drafted (submitted to "
            "verify), accepted (kept), wasted (drafted - accepted)",
            labels=("component", "kind"))
        reg.gauge(
            "paddle_spec_acceptance_rate",
            "Cumulative speculative acceptance rate (accepted / drafted)",
            labels=("component",)).labels(self._component).set_function(
                self._acceptance_rate)
        # ISSUE-18 phase-attributed latency: TTFT (acceptance -> first
        # generated token) and TPOT (mean inter-token gap after the first,
        # with pause time excluded — preemption is a scheduling decision,
        # not model latency) per tenant. Untenanted traffic rides the
        # "default" label, so the families are live on every continuous
        # scheduler — retirement always observes them.
        from ..observability.metrics import DEFAULT_LATENCY_BUCKETS
        ttft_hist = reg.histogram(
            "paddle_serving_ttft_seconds",
            "Time to first generated token (acceptance -> first token) by "
            "tenant; door-rejected requests are never sampled",
            labels=("component", "tenant"), buckets=DEFAULT_LATENCY_BUCKETS)
        tpot_hist = reg.histogram(
            "paddle_serving_tpot_seconds",
            "Mean time per output token after the first (paused time "
            "excluded) by tenant",
            labels=("component", "tenant"),
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5))
        # SLO gauges exist IFF a monitor is installed (exposition-lint
        # contract); with a flight recorder too, an alert edge dumps the
        # ring — the breach window's slot state survives the incident.
        if self.slo is not None:
            self.slo.bind_metrics(reg)
            if self.flight is not None:
                self.slo.on_alert(
                    lambda p: self.flight.mark_alert(
                        p.name, state=p.state(),
                        burn_fast=round(p.burn_rate("fast"), 4),
                        burn_slow=round(p.burn_rate("slow"), 4)))
        # ISSUE-19 utilization series exist IFF a ledger is installed (same
        # absent-iff-off exposition contract); the MFU gauge additionally
        # needs a known device peak — the ledger itself enforces that.
        if self.util is not None:
            self.util.bind_metrics(reg, component=self._component)
            self.metrics.attach_utilization(self.util)
        if self.flight is not None:
            occ = reg.gauge(
                "paddle_flightrec_ticks",
                "Flight-recorder ring state (occupancy = retained tick "
                "snapshots, capacity = ring bound, dropped = evicted)",
                labels=("component", "state"))
            occ.labels(self._component, "occupancy").set_function(
                lambda: float(self.flight.occupancy))
            occ.labels(self._component, "capacity").set_function(
                lambda: float(self.flight.capacity))
            occ.labels(self._component, "dropped").set_function(
                lambda: float(self.flight.dropped))
        return spec_counter, lora_counter, ttft_hist, tpot_hist

    def _acceptance_rate(self):
        with self._slot_lock:
            d, a = self._spec_drafted, self._spec_accepted
        return a / d if d else 0.0

    def _gen_timing(self, info):
        """Launch-latency histogram only: the base hook also counts
        batch*new_tokens as generated, but a tick's width includes masked
        idle slots — actual tokens are counted per sequence at retirement
        (_retire_ok) instead.

        Doubles as the post-ready compile sentinel's tap (ISSUE-13): once
        the AOT warmup armed it, any launch that had to cold-build its step
        program is a compile-surface violation — counted per program and
        reported to the chaos-suite witness (inference/warmup.py)."""
        self._last_launch = info    # ISSUE-19: tick fns read flops/launch_s
        self._decode_hist.labels(self._component, info["path"]).observe(
            info["launch_s"])
        if info["compiled"] and self._warm_armed.is_set():
            self._recompile_counter.labels(
                self._component, info["path"]).inc()
            _recompile_notify(self._component, info["path"])

    def _phase_count(self, phase):
        with self._slot_lock:
            if phase is None:       # live count
                return sum(1 for s in self._slots if s is not None)
            return sum(1 for s in self._slots
                       if s is not None and s.phase == phase)

    def _prefill_backlog(self):
        with self._slot_lock:
            return sum(s.plen - s.pos for s in self._slots
                       if s is not None and s.phase == _PREFILL)

    # ---------------------------------------------------------------- client
    def infer(self, ids, timeout=None, deadline=None, trace_id=None,
              max_new_tokens=None, temperature=None, top_k=None, spec=None,
              adapter=None, tenant=None):
        """One prompt in -> prompt + generated ids out.

        `max_new_tokens` (<= the server cap) asks for fewer tokens than the
        server-wide maximum; the sequence retires the moment it has them and
        its slot/blocks go to the next request — the aggregate-throughput
        win whole-request batching cannot give.

        `temperature` / `top_k` are PER-REQUEST sampler knobs (default
        greedy). They ride the step programs as traced per-slot arrays, so
        a greedy request and a temperature-0.8/top-k-40 request decode in
        the SAME tick of the SAME compiled program — mixed-sampler traffic
        never forks step programs (recompile-sentinel-pinned in tests).

        `spec` (tri-state) opts this request out of speculative decoding
        (`spec=False`) when the scheduler runs with spec_k > 0: the slot
        rides the same verify program with zero drafts. `spec=True` is a
        no-op beyond the default; it cannot force speculation on a
        scheduler configured without it.

        `adapter` (ISSUE-15) names a registered LoRA adapter; the request
        decodes through its low-rank delta in the SAME tick program as base
        and other-adapter batchmates. Unknown names (and any adapter on a
        registry-less scheduler) raise ValueError here, synchronously —
        HTTP maps it to 400, the X-Temperature taxonomy.

        `tenant` (ISSUE-17) bills the request to a TenantLedger tenant:
        weighted fair-share admission, the tenant's token-budget rate
        limit at the door (429 + computed Retry-After), and its priority
        tier for preemption. Unknown names (and any tenant on a
        ledger-less scheduler) raise ValueError — the X-Adapter taxonomy;
        None rides the ledger's built-in default tenant."""
        req = self._make_request([np.asarray(ids)], timeout, deadline,
                                 trace_id)
        if max_new_tokens is not None:
            req.max_new = max(1, min(int(max_new_tokens),
                                     self.max_new_tokens))
        if temperature is not None:
            req.temperature = float(temperature)
        if top_k is not None:
            req.top_k = int(top_k)
        if spec is not None:
            req.spec = bool(spec)
        self._route_adapter(req, adapter)
        self._route_tenant(req, tenant)
        return self._submit(req)

    def _route_adapter(self, req, adapter):
        """Validate-and-attach for infer/infer_stream's adapter= param.

        The name is checked NOW (a malformed request must fail before
        enqueue, 400-style) but resolved to a bank slot at ADMISSION —
        acquire() there takes the refcount pin for exactly the sequence's
        lifetime, and an unregister between submit and admit is then an
        admission failure, never a stale slot index."""
        if adapter is None:
            return
        if self.adapters is None:
            raise ValueError(
                "adapter routing needs an AdapterRegistry (scheduler "
                "adapters= knob); this scheduler serves the base model only")
        if not self.adapters.has(adapter):
            raise ValueError(f"unknown adapter {adapter!r}")
        req.adapter = adapter

    def _route_tenant(self, req, tenant):
        """Validate-and-attach for infer/infer_stream's tenant= param:
        unknown names fail NOW (400-style, before enqueue), None resolves
        to the ledger's default tenant, and a tenant on a ledger-less
        scheduler is a client misroute (same contract as _route_adapter)."""
        if tenant is None:
            if self.qos is not None:
                req.tenant = self.qos.resolve(None).name
            return
        if self.qos is None:
            raise ValueError(
                "tenant routing needs a TenantLedger (scheduler qos= "
                "knob); this scheduler serves untenanted traffic only")
        req.tenant = self.qos.resolve(tenant).name  # ValueError: unknown

    def infer_stream(self, ids, timeout=None, deadline=None, trace_id=None,
                     max_new_tokens=None, temperature=None, top_k=None,
                     spec=None, adapter=None, tenant=None):
        """Streaming twin of infer() (ISSUE-11): tokens arrive as the tick
        loop absorbs them instead of at retirement.

        Admission-time failures (ServerBusy / circuit open / malformed
        request) raise HERE, synchronously — an HTTP front end still maps
        them to proper 4xx/5xx statuses because no response bytes have
        flushed yet. The return value is an iterator yielding int64 arrays
        of newly generated tokens per tick-boundary flush; their
        concatenation is exactly infer()'s generated suffix (same sampler,
        same programs — streaming changes WHEN tokens are delivered, never
        WHICH). Terminal failures after acceptance (deadline mid-stream,
        shed, batch error) raise from the iterator; deadline semantics are
        identical to _await's client-side cancel."""
        req = self._make_request([np.asarray(ids)], timeout, deadline,
                                 trace_id)
        if max_new_tokens is not None:
            req.max_new = max(1, min(int(max_new_tokens),
                                     self.max_new_tokens))
        if temperature is not None:
            req.temperature = float(temperature)
        if top_k is not None:
            req.top_k = int(top_k)
        if spec is not None:
            req.spec = bool(spec)
        self._route_adapter(req, adapter)
        self._route_tenant(req, tenant)
        q: queue.Queue = queue.Queue()
        req.on_tokens = q.put       # published before enqueue (no races)
        self._start(req)            # raises Rejected/ValueError/503 here
        return self._stream_pump(req, q)

    def _stream_pump(self, req, q):
        """Generator half of infer_stream: drain the flush queue, mirroring
        _await's deadline-cancel / supervisor-heal loop between flushes."""
        try:
            while True:
                if req.deadline is None:
                    step = 0.1
                else:
                    rem = req.deadline.remaining()
                    if rem <= 0:
                        if req.cancel():
                            self.metrics.inc("timeouts")
                            self._observe(req)
                            if req.trace is not None:
                                req.trace.finish("timeout", cas="timeout",
                                                 where="client_stream")
                            raise DeadlineExceeded(
                                "inference request timed out mid-stream")
                        break   # lost the race: terminal outcome landed
                    step = min(0.1, rem)
                try:
                    yield np.asarray(q.get(timeout=step), np.int64)
                    continue
                except queue.Empty:
                    pass
                if req.event.is_set():
                    break
                try:
                    if self._sup.heal():
                        self.metrics.inc("batcher_restarts")
                except ServiceUnavailable as e:
                    self._fail(req, e)
                    raise
            # flushes that landed between the last drain and the terminal CAS
            while True:
                try:
                    yield np.asarray(q.get_nowait(), np.int64)
                except queue.Empty:
                    break
            if req.error is not None:
                raise req.error
        except GeneratorExit:
            # consumer walked away mid-stream (client disconnect): same
            # terminal path as a client-side timeout — the tick loop
            # reclaims the slot at the next boundary
            if req.cancel():
                self.metrics.inc("timeouts")
                self._observe(req)
                if req.trace is not None:
                    req.trace.finish("timeout", cas="timeout",
                                     where="stream_abandoned")
            raise
        finally:
            req.on_tokens = None

    def _admission_check(self, arrays, req=None):
        plen = len(arrays[0])
        total = plen + self.max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(
                f"request needs {total} tokens but max_seq_len is "
                f"{self.max_seq_len}; no retry can succeed")
        self.model._decode_validate(plen, self.max_new_tokens)
        need = self.kv_cache.blocks_for(total)
        self.admission.admit(self._queue.qsize(), cache=self.kv_cache,
                             blocks_needed=need)
        if self.qos is not None and req is not None:
            # tenant token-budget rate limit (ISSUE-17): charged at the
            # door with the request's worst-case token bill; a shed raises
            # ServerBusy carrying the bucket's computed time-to-refill —
            # HTTP 429 with a Retry-After derived from the tenant's rate
            want = (req.max_new if req.max_new is not None
                    else self.max_new_tokens)
            self.qos.charge(getattr(req, "tenant", None), plen + want)

    def pending(self) -> int:
        """Queued + in-flight + paused sequences (drain condition)."""
        return (self._queue.qsize() + len(self._backlog)
                + len(self._paused) + self._phase_count(None))

    def tenant_backlog(self) -> dict:
        """Queued (not yet slotted) PENDING requests by tenant: a
        scrape-time scan of the arrival queue + reorder backlog, so there
        is no incremental counter to drift across defer/retry/requeue
        paths. Feeds the paddle_tenant_backlog gauge and the autoscaler's
        per-tenant pressure signal."""
        if self.qos is None:
            return {}
        counts: dict = {}
        for r in list(self._queue.queue) + list(self._backlog):
            if r.state != _PENDING:
                continue
            name = self._tenant_spec_of(r).name
            counts[name] = counts.get(name, 0) + 1
        return counts

    def _tenant_spec_of(self, req):
        """Request -> TenantSpec; anything unroutable rides the default
        tenant (routing already 400'd truly unknown names — this is the
        tick loop, which must never fail on a stray request field)."""
        try:
            return self.qos.resolve(getattr(req, "tenant", None))
        except ValueError:
            return self.qos.resolve(None)

    # ------------------------------------------------------------- tick loop
    def _loop(self):
        # ISSUE-13 ready gate: no tick runs (and so no step program can
        # cold-build under traffic) until the aot-warmup thread finished.
        # Wait with a poll so close() during warmup still exits promptly.
        while self.warmup and not self._warm_done.wait(0.05):
            if self._stop.is_set():
                return
        if self.fallback_dense:
            # signature-mismatch degradation: the paged step programs would
            # scatter garbage; serve through the base collect-and-run loop
            # (GenerateBatchingPredictor._run_batch -> _run_dense)
            return super()._loop()
        try:
            while not self._stop.is_set():
                try:
                    if self._faults is not None:
                        self._faults.check("batcher.tick")  # ThreadDeath
                    self._admit()
                    if self._phase_count(None) == 0:
                        continue        # _admit parked briefly on the queue
                    self._busy = True
                    if self.util is not None:   # ISSUE-19 tick window opens
                        self.util.tick_begin()
                    try:
                        self._retire_unserviceable()
                        self._prefill_tick()
                        self._decode_tick()
                        self._util_tick()       # ISSUE-19 close BEFORE the
                        self._flight_tick()     # ring captures last_tick
                    finally:
                        self._busy = False
                except ThreadDeath:
                    # the dying thread strands no sequence: blocks go back to
                    # the pool, pending requests re-enter the queue, and the
                    # supervisor-healed thread re-runs them from scratch
                    self._abandon_slots()
                    raise
        finally:
            if self._stop.is_set():
                self._shutdown_slots()

    def _free_slot(self):
        with self._slot_lock:
            for i, s in enumerate(self._slots):
                if s is None:
                    return i
        return None

    def _admit(self):
        """Fill free slots from the queue (one tick's admissions).

        The reserve is atomic: a request either ends up fully reserved in a
        slot or the pool is untouched. On a dry pool the request defers or
        sheds (existing `_shed_or_defer` budget) and admission STOPS for
        this tick — blocks free as other slots retire, so later ticks
        retry; already-running slots never notice.

        With a TenantLedger (qos= knob) admission routes through
        `_qos_admit` instead: free slots go to the most under-served
        tenant's waiting work (paused sequences compete with new arrivals),
        then strictly more urgent waiters preempt the least urgent running
        sequences."""
        if self.qos is not None:
            return self._qos_admit()
        block = self._phase_count(None) == 0    # idle: park, don't spin
        while True:
            idx = self._free_slot()
            if idx is None:
                return
            try:
                req = self._next_request(block)
            except queue.Empty:
                return
            block = False
            if not self._usable(req):
                continue
            if not self._install_seq(idx, req):
                return

    def _install_seq(self, idx, req) -> bool:
        """Admit ONE usable request into free slot `idx`: pin its adapter,
        consult the prefix cache, atomically reserve its blocks, and place
        the sequence. Returns False only on a dry pool (CacheOutOfBlocks →
        `_shed_or_defer`; the caller stops admitting this tick); every
        other failure is THIS request's terminal and admission continues."""
        arr = req.arrays[0]
        plen = len(arr)
        max_new = (req.max_new if req.max_new is not None
                   else self.max_new_tokens)
        seq_n = next(self._rid)     # atomic draw (itertools.count)
        rid = ("cseq", seq_n)
        tr = req.trace
        traced = self.tracer.enabled
        ids64 = np.asarray(arr, np.int64)
        # ISSUE-15: pin the request's adapter slot FIRST — acquire
        # bumps the bank-row refcount for exactly the sequence's
        # lifetime (released in _evict_slot), so an unregister racing
        # this admission either loses (we hold the pin) or wins (the
        # name is gone and THIS request fails 400-style; the batch is
        # untouched). The uid seed keys the prefix lookup below: same
        # tokens under a different adapter can never share KV.
        aslot, aseed = 0, b""
        if self.adapters is not None:
            aname = getattr(req, "adapter", None)
            try:
                aslot, aseed = self.adapters.acquire(aname)
            except ThreadDeath:
                raise
            except Exception as e:
                self._fail(req, e)
                return True
            self._lora_requests_counter.labels(
                self._component,
                "base" if aname is None else aname).inc()
        hit, t_px = None, 0.0
        pc = self.prefix_cache
        if pc is not None:
            t_px = self.tracer.now_us() if traced else 0.0
            try:
                hit = pc.lookup(ids64, seed=aseed)  # kv.prefix_match
            except ThreadDeath:
                raise
            except Exception as e:
                # a broken index lookup is a cache MISS, never a failed
                # request — the cold path below is always correct
                if traced and tr is not None:
                    tr.child("prefix_lookup", t_px, self.tracer.now_us(),
                             error=repr(e))
                hit = None
        t_kv = self.tracer.now_us() if traced else 0.0
        try:
            self.kv_cache.reserve(
                rid, plen + max_new,
                shared=hit.pairs if hit is not None else None)
        except CacheOutOfBlocks as e:
            if traced and tr is not None:
                tr.child("kv_reserve", t_kv, self.tracer.now_us(),
                         error=repr(e))
            if self.adapters is not None:
                self.adapters.release(aslot)
            self._shed_or_defer(req, e)
            return False
        except Exception as e:
            # an eviction-path fault (kv.prefix_evict chaos) is THIS
            # request's admission failure, never a dead worker:
            # reserve's undo left the pool byte-identical, so fail the
            # one request and keep admitting (exactly-once terminal)
            if traced and tr is not None:
                tr.child("kv_reserve", t_kv, self.tracer.now_us(),
                         error=repr(e))
            if self.adapters is not None:
                self.adapters.release(aslot)
            self._fail(req, e)
            return True
        if traced and tr is not None:
            tr.child("kv_reserve", t_kv, self.tracer.now_us(),
                     blocks=self.kv_cache.blocks_for(plen + max_new))
        self._end_queue_wait([req])
        seq = _SlotSeq(req, rid, ids64, arr.dtype, max_new, seq_n)
        seq.adapter = aslot
        seq.adapter_seed = aseed
        if self.qos is not None:
            # ISSUE-17: bill the slot to its tenant — the inflight count is
            # held for exactly the RUNNING span (pause releases it, resume
            # re-takes it, every evict path drops it), and the expected
            # service cost advances the tenant's virtual-time clock ONCE,
            # here: _qos_pick admits the smallest clock first, which is what
            # makes steady-state throughput weight-proportional
            spec = self._tenant_spec_of(req)
            seq.tenant = spec.name
            seq.priority = spec.priority
            self.qos.acquire(spec.name, cost=plen + max_new)
            seq.qos_held = True
            self.qos.note_admitted(spec.name)
        seq.table = self.kv_cache.block_table(rid,
                                              pad_to=self.table_width)
        if hit is not None:
            # rows already resident after revalidation: reserve set the
            # committed length to the acquired shared blocks — chunked
            # prefill resumes at the first novel token (~O(new tokens))
            got = int(self.kv_cache.length(rid))
            seq.prefix_hit = got
            seq.pos = seq.length = got
            seq.digests = hit.digests
            if got:
                self.metrics.inc("prefix_hit_tokens", got)
                self._prefix_hit_counter.inc(got)
            if traced and tr is not None:
                tr.child("prefix_lookup", t_px, self.tracer.now_us(),
                         matched_blocks=len(hit.pairs),
                         hit_tokens=got)
        seq.t_admit = self._clock()     # queue phase ends here (ISSUE-18)
        with self._slot_lock:
            self._slots[idx] = seq
        self.metrics.inc("admitted_seqs")
        if tr is not None:
            tr.event("admitted", slot=idx, prompt_len=plen,
                     max_new=max_new)
        return True

    # ------------------------------------------------- multi-tenant QoS tick
    def _qos_admit(self):
        """Fair-share admission (qos= knob): free slots go to the waiting
        work — paused sequences AND queued arrivals, unified — of the most
        urgent tier's most under-served tenant; then strictly more urgent
        waiters preempt the least urgent running sequences. Host-side
        bookkeeping only: the step launches (and so the compile surface)
        are byte-identical to the untenanted scheduler's."""
        while True:     # drain arrivals into the reorder backlog
            try:
                self._backlog.append(self._queue.get_nowait())
            except queue.Empty:
                break
        if (not self._backlog and not self._paused
                and self._phase_count(None) == 0):
            try:        # fully idle: park briefly instead of spinning
                self._backlog.append(self._queue.get(timeout=0.05))
            except queue.Empty:
                return
        while True:
            idx = self._free_slot()
            if idx is None:
                break
            pick = self._qos_pick()
            if pick is None:
                break
            kind, item = pick
            if kind == "resume":
                self._resume_seq(idx, item)
            elif not self._install_seq(idx, item):
                return      # pool dry: stop admitting this tick
        self._preempt_for_priority()

    def _qos_pick(self):
        """Best waiting work item: ('resume', seq) | ('admit', req) | None.

        Order: priority tier first (lower = more urgent), then the
        tenant's fair-share deficit (inflight/weight — the MINIMUM is the
        most under-served, so contended slots converge to weight shares),
        then resumes before fresh admissions (a paused sequence holds
        blocks; finishing it frees memory), then arrival order."""
        while True:
            best = best_key = kind = None
            for s in self._paused:
                k = (s.priority, self.qos.fair_ratio(s.tenant), 0, s.order)
                if best_key is None or k < best_key:
                    best_key, best, kind = k, s, "resume"
            for pos, r in enumerate(self._backlog):
                spec = self._tenant_spec_of(r)
                k = (spec.priority, self.qos.fair_ratio(spec.name), 1, pos)
                if best_key is None or k < best_key:
                    best_key, best, kind = k, r, "admit"
            if best is None:
                return None
            if kind == "resume":
                try:
                    self._paused.remove(best)
                except ValueError:  # pragma: no cover - raced an evict
                    continue
                return ("resume", best)
            self._backlog.remove(best)
            if not self._usable(best):
                continue
            return ("admit", best)

    def _preempt_for_priority(self):
        """Priority preemption: while a waiting request (or paused
        sequence) is STRICTLY more urgent than the least urgent running
        sequence, pause that victim — blocks retained, slot state parked,
        tick width freed — and hand its slot to the waiter. Equal tiers
        never preempt each other (fair share handles those), so the loop
        terminates: each round strictly improves the worst running tier."""
        while self._backlog or self._paused:
            if self._free_slot() is not None:
                return      # width available; the admit loop already ran
            wprio = None
            for s in self._paused:
                wprio = (s.priority if wprio is None
                         else min(wprio, s.priority))
            for r in self._backlog:
                if r.state != _PENDING:
                    continue
                p = self._tenant_spec_of(r).priority
                wprio = p if wprio is None else min(wprio, p)
            if wprio is None:
                return
            with self._slot_lock:
                victim, vi = None, -1
                for i, s in enumerate(self._slots):
                    if s is None:
                        continue
                    if (victim is None or (s.priority, s.order)
                            > (victim.priority, victim.order)):
                        victim, vi = s, i
            if victim is None or victim.priority <= wprio:
                return
            self._pause_slot(vi, victim)
            idx = self._free_slot()
            pick = self._qos_pick() if idx is not None else None
            if pick is None:
                return      # victim resumes via a later tick's admit loop
            kind, item = pick
            if kind == "resume":
                self._resume_seq(idx, item)
            elif not self._install_seq(idx, item):
                return      # pool dry (the paused victim keeps its blocks)

    def _pause_slot(self, i, s):
        """Preempt a running sequence: park it off-slot with its blocks
        RETAINED (the rid stays reserved — preemption frees tick width,
        not memory; adapter pin included, so an unload can't race a paused
        sequence either) and release its tenant's fair-share count. The
        parked pos/tok/length/table bookkeeping is exactly the state a
        prefix-hit admission produces, so resume is plain continuation —
        bit-identical tokens, zero new compiled programs."""
        t0 = self.tracer.now_us() if self.tracer.enabled else 0.0
        with self._slot_lock:
            if self._slots[i] is s:
                self._slots[i] = None
        if s.qos_held:
            s.qos_held = False
            self.qos.release(s.tenant)
        s.t_pause = self._clock()   # paused phase opens (ISSUE-18)
        self._paused.append(s)
        self.metrics.inc("preempted_seqs")
        tr = s.req.trace
        if tr is not None:
            tr.child("preempt", t0, self.tracer.now_us(), slot=i,
                     phase=s.phase, committed=int(s.length))

    def _resume_seq(self, idx, s):
        """Reinstall a paused sequence into a free slot: its blocks and
        pos/length bookkeeping never left, so the next tick continues it
        exactly where it stopped (mid-prefill resumes its chunk walk at
        pos — the prefix-hit continuation path; mid-decode feeds tok back
        to the decode launch)."""
        t0 = self.tracer.now_us() if self.tracer.enabled else 0.0
        if self.qos is not None and not s.qos_held:
            self.qos.acquire(s.tenant)
            s.qos_held = True
        self._close_pause(s)
        with self._slot_lock:
            self._slots[idx] = s
        self.metrics.inc("resumed_seqs")
        tr = s.req.trace
        if tr is not None:
            tr.child("resume", t0, self.tracer.now_us(), slot=idx,
                     phase=s.phase, committed=int(s.length))

    def _next_request(self, block):
        """One queue pop under the admit policy.

        FIFO pops the arrival queue directly. shortest_prompt_first drains
        arrivals into a reorder backlog and admits the backlog's shortest
        prompt, tie-broken by the most urgent deadline, then arrival order
        (deterministic). The reorder window is only ever the set of
        requests waiting while a slot is free — a long prompt is delayed,
        never starved: it admits the moment it is the backlog minimum."""
        if self.admit_policy == "fifo":
            return (self._queue.get(timeout=0.05) if block
                    else self._queue.get_nowait())
        while True:                 # drain arrivals into the backlog
            try:
                self._backlog.append(self._queue.get_nowait())
            except queue.Empty:
                break
        if not self._backlog:
            if not block:
                raise queue.Empty
            self._backlog.append(self._queue.get(timeout=0.05))

        def urgency(item):
            pos, r = item        # backlog preserves arrival order
            rem = (r.deadline.remaining() if r.deadline is not None
                   else float("inf"))
            return (len(r.arrays[0]), rem, pos)
        _, best = min(enumerate(self._backlog), key=urgency)
        self._backlog.remove(best)
        return best

    # ----------------------------------------------------------- retirement
    def _evict_slot(self, i, s):
        """Free the slot and return its blocks to the pool (all retirement
        paths funnel here — blocks can never outlive their sequence)."""
        with self._slot_lock:
            if self._slots[i] is s:
                self._slots[i] = None
        self._release_seq(s)

    def _evict_paused(self, s):
        """Paused-sequence twin of _evict_slot: unpark and release (the
        blocks a preempted sequence retained must not outlive it either)."""
        try:
            self._paused.remove(s)
        except ValueError:  # pragma: no cover - already unparked
            pass
        self._release_seq(s)

    def _release_seq(self, s):
        """Return a sequence's held resources: tenant fair-share count,
        adapter bank pin, KV blocks. Idempotent on every leg (double-evict
        from shutdown racing retirement releases exactly once)."""
        if self.qos is not None and s.qos_held:
            s.qos_held = False
            self.qos.release(s.tenant)
        if self.adapters is not None and s.adapter != 0:
            # drop the admission-time bank-slot pin; zeroing first makes a
            # double-evict (shutdown racing retirement) release exactly once
            aslot, s.adapter = s.adapter, 0
            self.adapters.release(aslot)
        try:
            self.kv_cache.mark_done(s.rid)
            self.kv_cache.release(s.rid)
        except KeyError:    # pragma: no cover - already evicted/released
            pass

    # ------------------------------------------------ phase attribution (18)
    def _close_pause(self, s):
        """Fold an open pause interval into the sequence's paused-time
        accounting (called on resume and on any terminal path that can
        reach a still-parked sequence)."""
        if s.t_pause is None:
            return
        dt = max(0.0, self._clock() - s.t_pause)
        s.t_pause = None
        s.paused_s += dt
        if s.t_first is None:
            s.paused_pre_s += dt

    def _attribute(self, s, observe=True):
        """Close out a sequence's phase accounting at its terminal: park
        the {queue,prefill,paused,decode}_share dict on the request (the
        terminal CAS tags the terminal span with it) and, when `observe`,
        emit the per-tenant TTFT/TPOT samples and feed the SLO monitor.
        Retry paths pass observe=False — a re-batched request must not
        sample TTFT twice."""
        self._close_pause(s)
        req = s.req
        walls = phase_walls(req.t0, s.t_admit, s.t_first, self._clock(),
                            s.paused_s, s.paused_pre_s)
        req.attribution = attribution_shares(*walls)
        if not observe:
            return
        tenant = s.tenant if s.tenant is not None else "default"
        if s.t_first is not None and req.t0 is not None:
            ttft = max(0.0, s.t_first - req.t0)
            self._ttft_hist.labels(self._component, tenant).observe(ttft)
            if self.slo is not None:
                self.slo.observe_ttft(ttft, tenant=tenant)
            if s.n_tok > 1:
                # decode wall with post-first-token pauses excluded: a
                # preempted sequence's park time is a scheduling decision,
                # never charged to TPOT
                gap = max(0.0, (s.t_last - s.t_first)
                          - (s.paused_s - s.paused_pre_s))
                tpot = gap / (s.n_tok - 1)
                self._tpot_hist.labels(self._component, tenant).observe(tpot)
                if self.slo is not None:
                    self.slo.observe_tpot(tpot, tenant=tenant)

    def _terminal_good(self, error):
        """Availability verdict of one terminal outcome: good iff the HTTP
        status the error maps to is non-5xx (mirrors the server's
        _fail_http taxonomy — a 400/429 is the client's problem, not an
        availability hit)."""
        if error is None:
            return True
        status = getattr(error, "status", None)     # Rejected carries one
        if status is None:
            if isinstance(error, TimeoutError):
                status = 504
            elif isinstance(error, CacheOutOfBlocks):
                status = 503
            elif isinstance(error, ValueError):
                status = 400
            else:
                status = 500
        return int(status) < 500

    def _finish_req(self, req, result) -> bool:
        won = super()._finish_req(req, result)
        if won and self.slo is not None:
            self.slo.observe_terminal(
                True, tenant=getattr(req, "tenant", None))
        return won

    def _fail(self, req, error) -> bool:
        won = super()._fail(req, error)
        if won and self.slo is not None:
            self.slo.observe_terminal(
                self._terminal_good(error),
                tenant=getattr(req, "tenant", None))
        return won

    def _util_tick(self):
        """Close the utilization ledger's tick window (ISSUE-19): tick wall
        minus the recorded launch walls becomes the host gap, the per-kind
        flops land on the counters. Ledger failures never take the tick
        loop down (same contract as the flight ring)."""
        if self.util is None:
            return
        try:
            self.util.tick_end()
        except ThreadDeath:
            raise
        except Exception:       # pragma: no cover - telemetry must not bite
            pass

    def _util_launch(self, program, total_units, slot_units, spec_units=0):
        """Attribute the tick's just-returned launch to the ledger. The
        timing hook stashed the launch's flops/launch_s on this thread; a
        path mismatch means the hook never fired for this program (warmup
        interleave) — skip rather than misattribute."""
        info = self._last_launch
        if info is None or info.get("path") != program:
            return
        try:
            self.util.record_launch(program, info.get("flops"),
                                    info.get("launch_s", 0.0),
                                    total_units, slot_units, spec_units)
        except ThreadDeath:
            raise
        except Exception:       # pragma: no cover - telemetry must not bite
            pass

    def _flight_tick(self):
        """One flight-recorder capture at the tick boundary (ISSUE-18): the
        slot map with per-slot tenant/adapter/phase/progress, batch widths,
        KV block accounting, paused/pending depths and the ledger's fair
        ratios. Capture failures are swallowed — the postmortem ring must
        never take the tick loop down."""
        rec = self.flight
        if rec is None:
            return
        try:
            with self._slot_lock:
                slots = [None if s is None else {
                    "slot": i, "tenant": s.tenant, "adapter": int(s.adapter),
                    "phase": s.phase, "plen": s.plen, "pos": int(s.pos),
                    "generated": len(s.generated), "priority": s.priority,
                } for i, s in enumerate(self._slots)]
            live = [d for d in slots if d is not None]
            kv = self.kv_cache
            snap = {
                "slots": slots,
                "width": {
                    "prefill": sum(1 for d in live
                                   if d["phase"] == _PREFILL),
                    "decode": sum(1 for d in live if d["phase"] == _DECODE),
                    "free": self.max_slots - len(live),
                },
                "kv": {"in_use": int(kv.blocks_in_use),
                       "free": int(kv.free_blocks),
                       "evictable": int(kv.evictable_blocks)},
                "paused": len(self._paused),
                "pending": self._queue.qsize() + len(self._backlog),
            }
            if self.qos is not None:
                snap["fair_ratios"] = self.qos.fair_snapshot()
            if self.util is not None and self.util.last_tick is not None:
                # ISSUE-19: the tick's own flops/gap decomposition rides
                # the ring — /debug/ticks shows WHY MFU dipped (which
                # slots were empty, which drafts died)
                snap["util"] = self.util.last_tick
            rec.record(snap)
        except ThreadDeath:
            raise
        except Exception:       # pragma: no cover - capture must not bite
            pass

    def _retire_ok(self, i, s):
        out = np.concatenate(
            [s.ids, np.asarray(s.generated[:s.max_new], np.int64)])
        # index the generated tail BEFORE the audit-only set_length below
        # rewrites the committed length: only rows actually written are
        # indexable (a decode tick's final launch may sample past max_new,
        # but the in-program write ceiling drops those rows — cap to it)
        self._register_prefix(s, out, min(s.length, s.plen + s.max_new),
                              digests=None)
        try:
            self.kv_cache.set_length(s.rid, s.plen + s.max_new)
        except (KeyError, ValueError):  # pragma: no cover - audit-only state
            pass
        self._attribute(s)      # ISSUE-18: shares + TTFT/TPOT samples
        self._finish_req(s.req, out.astype(s.out_dtype))
        if self.qos is not None and s.tenant is not None:
            # useful tokens by tenant (ISSUE-17): the fairness bench's
            # numerator is work DELIVERED at retirement, not work admitted
            self.qos.account(s.tenant, len(s.generated[:s.max_new]))
        self._evict_slot(i, s)
        self.metrics.inc("retired_seqs")
        self._tokens_total.labels(self._component).inc(len(s.generated))

    def _retire_unserviceable(self):
        """Per token-step deadline/cancel semantics: at every tick boundary a
        sequence whose client cancelled, or whose deadline expired mid-
        decode, is retired and its blocks freed — exactly one terminal
        outcome via the request CAS, batchmates untouched."""
        for i, s in enumerate(list(self._slots)):
            if s is None:
                continue
            req = s.req
            if req.state != _PENDING:
                self.metrics.inc("cancelled_skipped")
                if req.trace is not None:
                    req.trace.event("slot_reclaimed_after_cancel", slot=i)
                self._evict_slot(i, s)
                self.metrics.inc("retired_seqs")
                continue
            if req.deadline is not None and req.deadline.expired():
                self._attribute(s)      # where the deadline actually went
                if self._fail(req, DeadlineExceeded(
                        "deadline expired mid-decode (continuous tick)")):
                    self.metrics.inc("expired_in_flight")
                self._evict_slot(i, s)
                self.metrics.inc("retired_seqs")
        # paused (preempted) sequences age under the same contract: a
        # cancelled or expired one frees its retained blocks NOW instead of
        # waiting to be resumed (exactly-once terminal via the request CAS)
        for s in list(self._paused):
            req = s.req
            if req.state != _PENDING:
                self.metrics.inc("cancelled_skipped")
                if req.trace is not None:
                    req.trace.event("paused_reclaimed_after_cancel")
                self._evict_paused(s)
                self.metrics.inc("retired_seqs")
            elif req.deadline is not None and req.deadline.expired():
                self._attribute(s)      # paused_share carries the park time
                if self._fail(req, DeadlineExceeded(
                        "deadline expired while preempted (paused)")):
                    self.metrics.inc("expired_in_flight")
                self._evict_paused(s)
                self.metrics.inc("retired_seqs")

    def _absorb(self, i, s, toks) -> bool:
        """Fold one tick's sampled tokens into the sequence; True if it
        retired. EOS freezes the remainder (parity with the in-scan
        sampler's finished mask, which resets per launch)."""
        eos = self.eos_token_id
        absorbed = 0
        for t in toks:
            if len(s.generated) >= s.max_new:
                break
            t = int(t)
            s.generated.append(t)
            absorbed += 1
            if eos is not None and t == eos:
                s.generated.extend([eos] * (s.max_new - len(s.generated)))
                break
        if absorbed:
            # ISSUE-18: first/last token stamps (tick-boundary resolution —
            # TPOT is the mean inter-token gap, and a tick absorbs
            # decode_steps tokens at once, so per-token jitter averages out)
            now = self._clock()
            if s.t_first is None:
                s.t_first = now
            s.t_last = now
            s.n_tok += absorbed
        self._flush_stream(s)
        if len(s.generated) >= s.max_new:
            self._retire_ok(i, s)
            return True
        return False

    def _flush_stream(self, s):
        """Tick-boundary streaming (ISSUE-11): push newly absorbed tokens
        through the request's on_tokens channel so infer_stream() clients
        see them NOW, not at retirement. A broken consumer never takes the
        tick loop down — the buffered result is still delivered."""
        cb = s.req.on_tokens
        if cb is None:
            return
        upto = min(len(s.generated), s.max_new)
        if upto <= s.flushed:
            return
        chunk = s.generated[s.flushed:upto]
        s.flushed = upto
        try:
            cb(list(chunk))
        except Exception:       # pragma: no cover - consumer bug
            pass

    def _fail_picks(self, picks, error, span_name, t0):
        self.breaker.record_failure()
        self.metrics.inc("batch_failures")
        reqs = [s.req for _, s in picks]
        self._span_each(reqs, span_name, t0, self.tracer.now_us(),
                        error=repr(error))
        for i, s in picks:
            # shares only (observe=False): a retry re-enters the queue and
            # must not sample TTFT twice — a retried-then-served request
            # samples once, at its eventual retirement
            self._attribute(s, observe=False)
            self._evict_slot(i, s)
            self._fail_or_retry(s.req, error)

    def _adapter_tick_kwargs(self, picks, reqs):
        """Per-tick LoRA launch kwargs (ISSUE-15): the traced [S] bank-index
        vector — each live slot gathers its adapter's rows, idle slots ride
        identity row 0. The host-side assembly is recorded as the
        `adapter_gather` span with the tick's distinct-adapter count (the
        heterogeneity dial: 1 means merged-weights would have done)."""
        if self.adapters is None:
            return {}
        traced = self.tracer.enabled
        t_g = self.tracer.now_us() if traced else 0.0
        aidx = np.zeros(self.max_slots, np.int32)
        for i, s in picks:
            aidx[i] = s.adapter
        if traced:
            self._span_each(reqs, "adapter_gather", t_g,
                            self.tracer.now_us(),
                            distinct_adapters=len({int(a) for a in aidx}))
        return dict(adapters=self.adapters, adapter_slots=aidx)

    # -------------------------------------------------------------- prefill
    def _prefill_tick(self):
        with self._slot_lock:
            pre = [(i, s) for i, s in enumerate(self._slots)
                   if s is not None and s.phase == _PREFILL]
        if not pre:
            return
        pre.sort(key=lambda t: t[1].order)      # oldest prompt first
        budget = self.prefill_token_budget
        picks = []
        for i, s in pre:
            if budget < 1:
                break
            take = min(self.prefill_chunk, s.plen - s.pos, budget)
            if take < 1:
                continue
            picks.append((i, s, take))
            budget -= take
        if not picks:
            return
        S, C = self.max_slots, self.prefill_chunk
        chunk = np.zeros((S, C), np.int64)
        offs = np.zeros(S, np.int64)
        lens = np.zeros(S, np.int64)
        temps = np.zeros(S, np.float32)
        tks = np.zeros(S, np.int32)
        tables = np.zeros((S, self.table_width), np.int32)
        for i, s, take in picks:
            chunk[i, :take] = s.ids[s.pos:s.pos + take]
            offs[i] = s.pos
            lens[i] = take
            temps[i] = s.temperature
            tks[i] = s.top_k
            tables[i] = s.table
        reqs = [s.req for _, s, _ in picks]
        akw = self._adapter_tick_kwargs([(i, s) for i, s, _ in picks], reqs)
        traced = self.tracer.enabled
        t0 = self.tracer.now_us() if traced else 0.0
        try:
            if self._faults is not None:
                self._faults.check("predictor.generate")
            tk = self.model.prefill_chunk(
                chunk, offs, lens, self.kv_cache, tables,
                temperature=temps, top_k=tks,
                eos_token_id=self.eos_token_id,
                decode_kernel=self.decode_kernel, seed=next(self._seed),
                timing_hook=self._timing_hook, **akw)
        except ThreadDeath:
            raise
        except Exception as e:
            self._fail_picks([(i, s) for i, s, _ in picks], e,
                             "prefill_chunk", t0)
            return
        self.breaker.record_success()
        self.metrics.inc("prefill_ticks")
        if self.util is not None:
            # ISSUE-19: useful positions are exactly each pick's take; the
            # S*C - sum(take) remainder (idle slots, chunk tail) is pad
            self._util_launch("prefill_chunk", S * C,
                              [(s.tenant, take) for _, s, take in picks])
        tk = np.asarray(tk._value if hasattr(tk, "_value") else tk)
        self._span_each(reqs, "prefill_chunk", t0, self.tracer.now_us(),
                        slots=len(picks),
                        tokens=int(sum(t for _, _, t in picks)))
        for i, s, take in picks:
            s.pos += take
            s.length = s.pos
            try:
                self.kv_cache.append_tokens(s.rid, take)
            except KeyError:    # pragma: no cover - raced an eviction
                pass
            self._register_prefix(s, s.ids, s.pos)
            if s.pos >= s.plen:
                s.phase = _DECODE
                s.tok = int(tk[i])
                self._absorb(i, s, [s.tok])

    def _register_prefix(self, s, tokens, committed, digests="prompt"):
        """Index this sequence's freshly COMMITTED full blocks (prefill
        chunks as they land, reusing the admission-time digest chain; the
        whole prompt+generation at retirement, rehashed since generated
        blocks have no precomputed digests). Registration is best-effort:
        an index failure must never take the sequence with it."""
        pc = self.prefix_cache
        if pc is None:
            return
        try:
            pc.register(s.rid, tokens,
                        digests=s.digests if digests == "prompt" else None,
                        length=int(committed), seed=s.adapter_seed)
        except ThreadDeath:
            raise
        except Exception:       # pragma: no cover - index bug, stay cold
            pass

    # --------------------------------------------------------------- decode
    def _decode_tick(self):
        if self.spec_k > 0:
            return self._verify_tick()
        with self._slot_lock:
            dec = [(i, s) for i, s in enumerate(self._slots)
                   if s is not None and s.phase == _DECODE]
        if not dec:
            return
        S, T = self.max_slots, self.decode_steps
        tok = np.zeros(S, np.int64)
        lengths = np.zeros(S, np.int64)
        maxlens = np.zeros(S, np.int64)
        active = np.zeros(S, bool)
        temps = np.zeros(S, np.float32)
        tks = np.zeros(S, np.int32)
        tables = np.zeros((S, self.table_width), np.int32)
        for i, s in dec:
            tok[i] = s.tok
            lengths[i] = s.length
            maxlens[i] = s.plen + s.max_new   # write ceiling: reserved rows
            active[i] = True
            temps[i] = s.temperature
            tks[i] = s.top_k
            tables[i] = s.table
        reqs = [s.req for _, s in dec]
        akw = self._adapter_tick_kwargs(dec, reqs)
        traced = self.tracer.enabled
        t0 = self.tracer.now_us() if traced else 0.0
        try:
            if self._faults is not None:
                self._faults.check("predictor.generate")
            toks = self.model.decode_step(
                tok, lengths, active, self.kv_cache, tables, steps=T,
                max_lens=maxlens, temperature=temps, top_k=tks,
                eos_token_id=self.eos_token_id,
                decode_kernel=self.decode_kernel, seed=next(self._seed),
                timing_hook=self._timing_hook, **akw)
        except ThreadDeath:
            raise
        except Exception as e:
            self._fail_picks(dec, e, "decode_step", t0)
            return
        self.breaker.record_success()
        self.metrics.inc("decode_ticks")
        toks = np.asarray(toks._value if hasattr(toks, "_value") else toks)
        self._span_each(reqs, "decode_step", t0, self.tracer.now_us(),
                        slots=len(dec), steps=T)
        units = []
        for i, s in dec:
            s.length += T
            s.tok = int(toks[i, -1])
            n0 = s.n_tok
            self._absorb(i, s, toks[i])
            # ISSUE-19: useful = tokens the sequence actually ABSORBED this
            # tick (EOS-frozen / over-cap rows are pad, like idle slots)
            units.append((s.tenant, s.n_tok - n0))
        if self.util is not None:
            self._util_launch("decode_step", S * T, units)

    def _verify_tick(self):
        """Speculative decode tick (spec_k > 0): draft on the host, verify
        in ONE fixed-width `verify_step` launch across all decoding slots.

        Per slot the drafted width is min(drafter proposal, SPARE width) —
        spare = tokens still owed minus the launch's guaranteed one, so a
        slot about to retire rides along with zero drafts instead of
        forking a narrower program. Rollback on rejection is length
        bookkeeping only (verify_step's contract); the KV ceiling stays
        the reserved plen + max_new exactly like the decode tick."""
        with self._slot_lock:
            dec = [(i, s) for i, s in enumerate(self._slots)
                   if s is not None and s.phase == _DECODE]
        if not dec:
            return
        S, K = self.max_slots, self.spec_k
        chunk = np.zeros((S, K + 1), np.int64)
        offs = np.zeros(S, np.int64)
        dlens = np.zeros(S, np.int64)
        maxlens = np.zeros(S, np.int64)
        active = np.zeros(S, bool)
        temps = np.zeros(S, np.float32)
        tks = np.zeros(S, np.int32)
        tables = np.zeros((S, self.table_width), np.int32)
        for i, s in dec:
            # shared-prefix safety (ISSUE-11): a verify launch writes its
            # whole window at [length, length+1+K) and rejection "rollback"
            # is length bookkeeping only — length never drops below plen,
            # and a prefix hit covers at most plen-1 tokens, so a verify
            # tick can never write into (or roll back into) a shared block
            assert s.length >= s.plen > s.prefix_hit, \
                (f"verify tick would touch shared prefix rows: "
                 f"length={s.length} plen={s.plen} hit={s.prefix_hit}")
            chunk[i, 0] = s.tok
            offs[i] = s.length
            maxlens[i] = s.plen + s.max_new
            active[i] = True
            temps[i] = s.temperature
            tks[i] = s.top_k
            tables[i] = s.table
            spare = s.max_new - len(s.generated) - 1
            if s.spec and spare > 0:
                hist = np.concatenate(
                    [s.ids, np.asarray(s.generated, np.int64)])
                prop = np.asarray(self._drafter.draft(hist, K),
                                  np.int64).reshape(-1)[:K]
                n = min(len(prop), spare)
                if n > 0:
                    chunk[i, 1:1 + n] = prop[:n]
                    dlens[i] = n
        reqs = [s.req for _, s in dec]
        akw = self._adapter_tick_kwargs(dec, reqs)
        traced = self.tracer.enabled
        t0 = self.tracer.now_us() if traced else 0.0
        try:
            if self._faults is not None:
                self._faults.check("predictor.generate")
            acc, nxt = self.model.verify_step(
                chunk, offs, dlens, active, self.kv_cache, tables,
                max_lens=maxlens, temperature=temps, top_k=tks,
                decode_kernel=self.decode_kernel, seed=next(self._seed),
                timing_hook=self._timing_hook, **akw)
        except ThreadDeath:
            raise
        except Exception as e:
            self._fail_picks(dec, e, "verify_step", t0)
            return
        self.breaker.record_success()
        self.metrics.inc("verify_ticks")
        acc = np.asarray(acc._value if hasattr(acc, "_value") else acc)
        nxt = np.asarray(nxt._value if hasattr(nxt, "_value") else nxt)
        drafted = int(sum(dlens[i] for i, _ in dec))
        accepted = int(sum(acc[i] for i, _ in dec))
        self._span_each(reqs, "verify_step", t0, self.tracer.now_us(),
                        slots=len(dec), drafted=drafted, accepted=accepted)
        self._spec_counter.labels(self._component, "drafted").inc(drafted)
        self._spec_counter.labels(self._component, "accepted").inc(accepted)
        self._spec_counter.labels(self._component,
                                  "wasted").inc(drafted - accepted)
        with self._slot_lock:
            self._spec_drafted += drafted
            self._spec_accepted += accepted
        units = []
        for i, s in dec:
            a = int(acc[i])
            s.length += 1 + a   # committed rows: accepted prefix + emitted
            s.tok = int(nxt[i])
            n0 = s.n_tok
            self._absorb(i, s, [int(t) for t in chunk[i, 1:1 + a]]
                         + [s.tok])
            # ISSUE-19: useful = absorbed (accepted prefix + the emitted
            # token, minus any over-cap shortfall); rejected drafts are
            # spec_waste; the rest of the S*(K+1) window is pad
            units.append((s.tenant, s.n_tok - n0))
        if self.util is not None:
            self._util_launch("verify_step", S * (K + 1), units,
                              spec_units=drafted - accepted)

    # ------------------------------------------------------------- lifecycle
    def _abandon_slots(self):
        """ThreadDeath path: free every slot's blocks; still-pending
        requests re-enter the queue and re-run from scratch after the
        supervisor heals the thread (their chunked-prefill progress is
        lost with the thread — correctness over cleverness)."""
        for i, s in enumerate(list(self._slots)):
            if s is None:
                continue
            self._evict_slot(i, s)
            if s.req.state == _PENDING:
                if s.req.trace is not None:
                    s.req.trace.event("requeued_after_thread_death")
                self._enqueue(s.req)
        for s in list(self._paused):
            # paused sequences lose their progress with the thread too:
            # blocks back to the pool, still-pending requests re-enter the
            # queue and re-run from scratch (correctness over cleverness)
            self._evict_paused(s)
            if s.req.state == _PENDING:
                if s.req.trace is not None:
                    s.req.trace.event("requeued_after_thread_death")
                self._enqueue(s.req)

    def _shutdown_slots(self):
        """stop() path: nobody hangs on a closed scheduler."""
        for i, s in enumerate(list(self._slots)):
            if s is None:
                continue
            self._fail(s.req, ServiceUnavailable("predictor closed",
                                                 retry_after=None))
            self._evict_slot(i, s)
        for s in list(self._paused):
            self._fail(s.req, ServiceUnavailable("predictor closed",
                                                 retry_after=None))
            self._evict_paused(s)
        self._drain_backlog()

    def _drain_backlog(self):
        """Backlog twin of close()'s queue drain: requests parked in the
        admit-policy reorder buffer get a terminal outcome too."""
        while True:
            try:
                r = self._backlog.popleft()
            except IndexError:
                break
            self._fail(r, ServiceUnavailable("predictor closed",
                                             retry_after=None))

    def close(self):
        super().close()
        self._drain_backlog()
