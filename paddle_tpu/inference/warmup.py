"""AOT warmup + the post-ready compile sentinel (ISSUE-13 runtime half).

The compile-surface lint (analysis/compilesurface.py) proves a serving
configuration's program inventory is CLOSED; this module makes the runtime
honor it:

* ``AOTWarmup`` derives the continuous scheduler's ServingConfig, takes its
  manifest program keys, and launches each step program ONCE with fully
  idle inputs (all slots masked, zero chunk lengths) so every cache key
  lands in the shared ``GenerationMixin._generate_cache`` before the
  predictor reports ready. Idle launches are write-free: the valid masks
  drop every KV scatter and commit() re-installs byte-identical pools, so
  warmup is safe next to a live pool. With ``cache_dir`` set, XLA's
  persistent compilation cache turns a process restart into a warm start
  (trace only — the docs/DEPLOYMENT.md cold-start runbook).

* The **post-ready compile sentinel** is the serving twin of the PR 4
  training sentinel (observability/training.py StepMonitor): once warmup
  has covered the manifest, any ``_runner_for`` cold build is a contract
  violation — the scheduler counts it in
  ``paddle_serving_recompiles_total{component,program}`` and notifies the
  active ``CompileSentinel``, which every chaos-marked test arms
  (tests/conftest.py) and fails on. Launch-argument shapes are
  fingerprinted with the SAME helper the training sentinel uses
  (jit/fingerprint.py), so the two sentinels cannot drift on what "the
  same program" means.
"""
from __future__ import annotations

import collections
import time

import numpy as np

from ..analysis.compilesurface import ServingConfig
from ..analysis.lockwitness import make_lock
from ..jit.fingerprint import aval_fingerprint

__all__ = ["AOTWarmup", "CompileSentinel", "serving_config_of",
           "enable_persistent_compile_cache", "activate", "deactivate",
           "notify"]


# ------------------------------------------------------------ the sentinel
class CompileSentinel:
    """Records post-ready cold builds. Appends are deque-atomic, so the
    batcher thread writes and the test thread reads without a lock."""

    def __init__(self):
        self.violations = collections.deque(maxlen=256)

    def record(self, component, program):
        self.violations.append((component, program))


_ACTIVE = None
_ACTIVE_LOCK = make_lock("warmup._ACTIVE_LOCK")


def activate(sentinel: CompileSentinel) -> CompileSentinel:
    """Install `sentinel` as the process-wide witness (chaos fixture)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = sentinel
    return sentinel


def deactivate():
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = None


def notify(component, program):
    """Called by the scheduler's timing hook on a post-ready cold build."""
    s = _ACTIVE
    if s is not None:
        s.record(component, program)


# ------------------------------------------------------------- the warmup
def serving_config_of(predictor) -> ServingConfig:
    """The lint-side ServingConfig a live continuous predictor embodies —
    the bridge between the static pass and the runtime (drift between the
    two shows up as AOTWarmup 'missing' keys, not as silence)."""
    return ServingConfig(
        name=getattr(predictor, "_component", "serving"),
        slots=predictor.max_slots,
        prefill_chunk=predictor.prefill_chunk,
        decode_steps=predictor.decode_steps,
        spec_k=predictor.spec_k,
        eos_token_id=predictor.eos_token_id,
        max_seq_len=predictor.max_seq_len,
        kv_signature=tuple(predictor.kv_cache.signature()),
        decode_kernel=predictor.decode_kernel,
        ids_dtype="int64",
        adapter_signature=(
            predictor.adapters.signature()
            if getattr(predictor, "adapters", None) is not None else None),
    )


def enable_persistent_compile_cache(cache_dir):
    """Point XLA's persistent compilation cache at `cache_dir` and lower
    the entry thresholds so every step program caches (the defaults skip
    fast compiles). A restarted process with the same dir pays trace time
    only — the cold-start runbook knob (docs/DEPLOYMENT.md).

    The cache backend initializes lazily at the process's FIRST compile
    and ignores later config updates — and by the time the warmup thread
    runs, building the model has already compiled something. reset_cache()
    forces re-initialization against the new dir (it only drops the stale
    backend handle, not any compiled program)."""
    import jax

    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except Exception:       # older jax: knob absent, defaults apply
            pass
    try:
        from jax._src.compilation_cache import reset_cache
        reset_cache()
    except Exception:           # private API moved: first-compile-wins then
        pass


class AOTWarmup:
    """Compile a continuous predictor's manifest programs before ready.

    run() launches each active step program once with idle inputs, then
    audits coverage: every derived cache key must be present in the
    model's runner cache afterwards. The returned stats dict is what the
    scheduler publishes through ``warm_stats()``:

        programs      manifest size for this config
        compiled      programs this run cold-built (0 on a warm restart
                      of a shared-model fleet replica)
        missing       derived keys NOT in the runner cache after warmup —
                      non-empty means static/runtime drift; the sentinel
                      does not arm (warmup_incomplete, see scheduler)
        fingerprints  {path: aval fingerprint of the warmup launch args}
                      (jit/fingerprint.py — shared with StepMonitor)
        seconds       wall time of the warmup launches
    """

    def __init__(self, predictor, *, cache_dir=None, tracer=None):
        self._pred = predictor
        self._cache_dir = cache_dir
        self._tracer = tracer

    def config(self) -> ServingConfig:
        return serving_config_of(self._pred)

    def programs(self):
        return self.config().program_keys()

    def _launch(self, path):
        """One idle-shaped launch of `path`; returns the launch args'
        aval fingerprint. Masks make these write-free: chunk_lens == 0
        drops every prefill scatter, active == False drops decode/verify
        writes, and commit() re-installs equal pools."""
        pred = self._pred
        model = pred.model
        S, W = pred.max_slots, pred.table_width
        kv, kern = pred.kv_cache, pred.decode_kernel
        tables = np.zeros((S, W), np.int32)
        zeros_i = np.zeros((S,), np.int64)
        idle = np.zeros((S,), bool)
        # LoRA-enabled predictors warm the BANKED program variant: an
        # all-slot-0 (identity) index builds the exact program every later
        # adapter mix reuses — the cache key carries only the bank shape
        ad = getattr(pred, "adapters", None)
        akw = ({} if ad is None else
               dict(adapters=ad, adapter_slots=np.zeros((S,), np.int32)))
        if path == "prefill_chunk":
            args = (np.zeros((S, pred.prefill_chunk), np.int64),
                    zeros_i, zeros_i, kv, tables)
            model.prefill_chunk(*args, eos_token_id=pred.eos_token_id,
                                decode_kernel=kern, seed=0, **akw)
        elif path == "decode_step":
            args = (zeros_i, zeros_i, idle, kv, tables)
            model.decode_step(*args, steps=pred.decode_steps,
                              eos_token_id=pred.eos_token_id,
                              decode_kernel=kern, seed=0, **akw)
        elif path == "verify_step":
            args = (np.zeros((S, pred.spec_k + 1), np.int64),
                    zeros_i, zeros_i, idle, kv, tables)
            model.verify_step(*args, decode_kernel=kern, seed=0, **akw)
        else:
            raise ValueError(f"no warmup launch for path {path!r}")
        return aval_fingerprint(args[:3], None)

    def run(self) -> dict:
        pred = self._pred
        t0 = time.perf_counter()
        tr = self._tracer
        t_us = tr.now_us() if tr is not None and tr.enabled else None
        if self._cache_dir:
            enable_persistent_compile_cache(self._cache_dir)
        cfg = self.config()
        keys = cfg.program_keys()
        cache = pred.model._runner_cache()
        before = set(cache)
        fingerprints = {}
        for path in cfg.active_paths():
            if pred._stop.is_set():     # closing mid-warmup: stop cleanly
                break
            fingerprints[path] = self._launch(path)
        after = set(pred.model._runner_cache())
        missing = [k for k in keys if k not in after]
        stats = {
            "programs": len(keys),
            "compiled": len(after - before),
            "missing": missing,
            "fingerprints": fingerprints,
            "seconds": time.perf_counter() - t0,
        }
        if t_us is not None:
            tr.record("aot_warmup", t_us, tr.now_us(), trace_id="warmup",
                      tags={"programs": stats["programs"],
                            "compiled": stats["compiled"],
                            "missing": len(missing)})
        return stats
