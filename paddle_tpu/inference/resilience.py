"""Resilience primitives for the serving stack.

Reference role: the scheduling/backpressure half of production LLM servers —
vLLM's block-pool admission (Kwon et al., SOSP 2023) and Orca's
iteration-level scheduling (Yu et al., OSDI 2022) both treat memory pressure
and stragglers as scheduling inputs, not exceptions. This module is the
host-side toolkit the batching predictors build on:

* ``Deadline`` — one absolute expiry per request, propagated HTTP → queue →
  decode launch, so a request times out exactly once wherever it happens
  to be when the clock runs out.
* ``ServerBusy`` / ``ServiceUnavailable`` — typed load-shed rejections that
  the HTTP layer maps to 429/503 + ``Retry-After`` (clients should back off
  and retry; a mid-batch ``CacheOutOfBlocks`` tells them nothing).
* ``AdmissionController`` — reject at the door (queue depth, KV-pool
  pressure, oversized requests) instead of failing mid-batch.
* ``CircuitBreaker`` — trip after repeated predictor failures, fail fast
  while open, half-open a single probe after a cooldown.
* ``Supervisor`` — restart a dead worker thread with capped, backed-off
  restarts.
* ``ServingMetrics`` — thread-safe terminal-outcome counters + latency tail,
  the observability contract the chaos tests and bench assert against.

Everything takes an injectable ``clock`` so the chaos tests drive expiry by
skewing time instead of sleeping (see inference/faults.py).
"""
from __future__ import annotations

import random
import time

from ..analysis.lockwitness import make_lock
from ..observability.metrics import DEFAULT_LATENCY_BUCKETS, MetricsRegistry

__all__ = [
    "DeadlineExceeded", "Rejected", "ServerBusy", "ServiceUnavailable",
    "Deadline", "AdmissionController", "CircuitBreaker", "Supervisor",
    "ServingMetrics",
]


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed (in queue, mid-batch, or client-side).

    Subclasses TimeoutError so pre-existing callers of
    ``BatchingPredictor.infer(timeout=...)`` keep working unchanged."""


class Rejected(RuntimeError):
    """Base for load-shed rejections; carries the HTTP mapping."""

    status = 503

    def __init__(self, msg, retry_after=None):
        super().__init__(msg)
        self.retry_after = retry_after


class ServerBusy(Rejected):
    """Transient overload (queue full / KV pool exhausted) -> HTTP 429."""

    status = 429


class ServiceUnavailable(Rejected):
    """Not serving (draining, breaker open, worker dead) -> HTTP 503.

    ``permanent=True`` marks a 503 no amount of client retrying will fix —
    today that is exactly one case: a Supervisor whose restart budget is
    spent (the worker is dead for good). The ReplicaFleet router keys replica
    death off this flag instead of string-matching the message."""

    def __init__(self, msg, retry_after=None, permanent=False):
        super().__init__(msg, retry_after=retry_after)
        self.permanent = bool(permanent)


class Deadline:
    """Absolute expiry on an injectable monotonic clock."""

    __slots__ = ("at", "clock")

    def __init__(self, at, clock=time.monotonic):
        self.at = float(at)
        self.clock = clock

    @classmethod
    def after(cls, seconds, clock=time.monotonic):
        return cls(clock() + float(seconds), clock)

    def remaining(self) -> float:
        return self.at - self.clock()

    def expired(self) -> bool:
        return self.clock() >= self.at


class AdmissionController:
    """Admit-or-reject at submission time.

    Rejecting at the door is the whole game: a request that will sit in a
    full queue or OOM the pool mid-batch costs a batch slot, pool churn, and
    a confusing 500; rejecting it here costs one exception and gives the
    client a ``Retry-After`` hint instead."""

    def __init__(self, max_queue_depth=256, high_water=1.0, retry_after=0.5):
        self.max_queue_depth = int(max_queue_depth)
        self.high_water = float(high_water)     # live-utilization shed point
        self.retry_after = float(retry_after)

    def admit(self, queue_depth, cache=None, blocks_needed=None):
        """Raises ServerBusy (retryable) on overload. Oversized requests that
        can NEVER fit raise ValueError (a retry cannot fix the request)."""
        if queue_depth >= self.max_queue_depth:
            raise ServerBusy(
                f"queue full ({queue_depth} >= {self.max_queue_depth})",
                retry_after=self.retry_after)
        if cache is not None and blocks_needed is not None:
            if blocks_needed > cache.num_blocks:
                raise ValueError(
                    f"request needs {blocks_needed} blocks but the whole "
                    f"pool is {cache.num_blocks}; no retry can succeed")
            if cache.live_utilization >= self.high_water:
                raise ServerBusy(
                    f"KV pool at {cache.live_utilization:.0%} live "
                    f"utilization (high water {self.high_water:.0%})",
                    retry_after=self.retry_after)


class CircuitBreaker:
    """closed -> open after N consecutive failures -> half-open after a
    cooldown (one probe) -> closed on probe success, re-open on failure."""

    def __init__(self, failure_threshold=5, reset_after=1.0,
                 clock=time.monotonic):
        self.failure_threshold = int(failure_threshold)
        self.reset_after = float(reset_after)
        self.clock = clock
        self._lock = make_lock("resilience.CircuitBreaker._lock")
        self._failures = 0
        self._opened_at = None
        self._probing = False
        self.trips = 0

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if self.clock() - self._opened_at >= self.reset_after:
                return "half-open"
            return "open"

    def allow(self) -> bool:
        """May a new call proceed? Half-open admits exactly one probe."""
        with self._lock:
            if self._opened_at is None:
                return True
            if self.clock() - self._opened_at < self.reset_after:
                return False
            if self._probing:
                return False
            self._probing = True
            return True

    def retry_after(self) -> float:
        with self._lock:
            if self._opened_at is None:
                return 0.0
            return max(0.0, self.reset_after - (self.clock() - self._opened_at))

    def record_success(self):
        with self._lock:
            self._failures = 0
            self._opened_at = None
            self._probing = False

    def record_failure(self):
        with self._lock:
            self._failures += 1
            was_open = self._opened_at is not None
            if self._probing or self._failures >= self.failure_threshold:
                self._opened_at = self.clock()   # (re)open; restart cooldown
                self._probing = False
                if not was_open:
                    self.trips += 1


class Supervisor:
    """Restart a dead worker thread, with capped exponential backoff.

    heal() is called from request paths (submit AND the client wait loop), so
    a batcher that dies with requests still queued is restarted by the very
    clients waiting on it — no dedicated watchdog thread to leak."""

    def __init__(self, factory, name="worker", max_restarts=5, backoff=0.0,
                 sleep=time.sleep):
        self._factory = factory         # () -> started-able threading.Thread
        self.name = name
        self.max_restarts = int(max_restarts)
        self.backoff = float(backoff)
        self._sleep = sleep
        self._lock = make_lock("resilience.Supervisor._lock")
        self.restarts = 0
        self.thread = None

    def start(self):
        with self._lock:    # same guard as heal(): `thread` has ONE lockset
            self.thread = self._factory()
            self.thread.start()
            return self.thread

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()

    def dead(self) -> bool:
        """Permanently down: the worker is not running and the restart
        budget is spent (heal() would raise). The fleet's replica-state
        gauge reads this without triggering a heal."""
        return not self.alive() and self.restarts >= self.max_restarts

    def heal(self) -> bool:
        """Restart the worker if it died. True if a restart happened; raises
        ServiceUnavailable once the restart budget is spent (at that point
        the service is genuinely down and clients should go elsewhere)."""
        if self.alive():
            return False
        with self._lock:
            if self.alive():                      # lost the race: healed
                return False
            if self.restarts >= self.max_restarts:
                raise ServiceUnavailable(
                    f"{self.name} dead after {self.restarts} restarts",
                    retry_after=None, permanent=True)
            self.restarts += 1
            if self.backoff:
                self._sleep(min(self.backoff * (2 ** (self.restarts - 1)),
                                1.0))
            self.thread = self._factory()
            self.thread.start()
            return True


class ServingMetrics:
    """Terminal-outcome counters + latency tail, re-based on the typed
    observability registry (paddle_tpu/observability/metrics.py).

    Conservation contract (pinned by the chaos tests and the pressure
    bench): every ACCEPTED request increments exactly one of
    ``completed`` / ``failed`` / ``timeouts``; admission rejections increment
    ``rejected_busy`` / ``rejected_unavailable`` instead and are never
    accepted. Anything else (deferred, retries, ...) is free-running
    telemetry outside the conservation sum.

    Every ``inc``/``observe_latency`` ALSO lands in the Prometheus registry:
    counters as ``paddle_serving_events_total{component=...,event=...}``
    (the conservation sum is therefore checkable straight off the /metrics
    exposition) and latencies as the
    ``paddle_serving_request_latency_seconds`` histogram. The legacy
    ``snapshot()`` JSON shape is unchanged.

    The latency reservoir is a UNIFORM sample (Vitter's algorithm R): with
    the old append-until-full buffer, sample 4097+ was silently dropped and
    p99 froze minutes into a long run — late-arriving tail latencies now
    displace random earlier samples so the percentiles keep tracking the
    live distribution."""

    _LAT_CAP = 4096

    def __init__(self, registry=None, component="serving", rng=None):
        self._lock = make_lock("resilience.ServingMetrics._lock")
        self._counters: dict[str, int] = {}
        self._latencies: list[float] = []
        self._lat_seen = 0                      # total observations ever
        self._rng = rng if rng is not None else random.Random(0x7A11)
        self.component = str(component)
        self.registry = registry if registry is not None else MetricsRegistry()
        self._prom_events = self.registry.counter(
            "paddle_serving_events_total",
            "Serving lifecycle events by component; conservation: "
            "accepted == completed + failed + timeouts",
            labels=("component", "event"))
        self._prom_latency = self.registry.histogram(
            "paddle_serving_request_latency_seconds",
            "Accepted-request latency to terminal outcome",
            labels=("component",), buckets=DEFAULT_LATENCY_BUCKETS)
        self._utilization = None

    def attach_utilization(self, ledger):
        """ISSUE-19: ride the utilization ledger's compact block on every
        snapshot() — operators get mfu / flops-by-kind / host-gap tail from
        the JSON /metrics page without a Prometheus scrape (mirrors the
        PR 18 tracer/flight blocks)."""
        with self._lock:
            self._utilization = ledger

    def inc(self, name, n=1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n
        self._prom_events.labels(self.component, name).inc(n)

    def get(self, name) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def observe_latency(self, seconds):
        v = float(seconds)
        with self._lock:
            self._lat_seen += 1
            if len(self._latencies) < self._LAT_CAP:
                self._latencies.append(v)
            else:
                # Vitter R: keep each of the n samples with P = CAP/n
                j = self._rng.randrange(self._lat_seen)
                if j < self._LAT_CAP:
                    self._latencies[j] = v
        self._prom_latency.labels(self.component).observe(v)

    @staticmethod
    def _pct(sorted_vals, q):
        if not sorted_vals:
            return None
        i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
        return sorted_vals[i]

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._counters)
            lat = sorted(self._latencies)
        for q, name in ((0.50, "p50_ms"), (0.95, "p95_ms"), (0.99, "p99_ms")):
            v = self._pct(lat, q)
            if v is not None:
                out[name] = round(v * 1000.0, 3)
        if self._utilization is not None:
            out["utilization"] = self._utilization.metrics_block()
        return out
