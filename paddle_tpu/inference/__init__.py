"""paddle.inference: load-and-serve without the model class.

Reference: paddle/fluid/inference/api/analysis_predictor.cc (AnalysisPredictor)
+ python/paddle/inference/wrapper.py (Config, create_predictor, input/output
handles). TPU-native shape: the "analysis" passes are XLA's job; the predictor
wraps a deserialized jax.export program (saved by ``paddle.jit.save`` with
input_spec), compiles per concrete input signature, and keeps weights resident
on device across ``run()`` calls.
"""
from __future__ import annotations

import numpy as np


class Config:
    """Reference: inference Config — model path + execution knobs."""

    def __init__(self, prog_file=None, params_file=None):
        # paddle convention: Config("path/model") with side files derived
        self._model_path = prog_file
        self._batch_poly = True
        self._device = None  # None = jax default (TPU when present)
        self._memory_optim = True

    def set_model(self, path):
        self._model_path = path

    def model_path(self):
        return self._model_path

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def switch_use_feed_fetch_ops(self, flag):  # compat no-op
        pass

    def enable_custom_device(self, device_type, device_id=0):
        self._device = device_type


class _Handle:
    """Input/output tensor handle (reference: ZeroCopyTensor role)."""

    def __init__(self):
        self._array = None

    def copy_from_cpu(self, arr):
        self._array = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        return self._array

    def reshape(self, shape):
        if self._array is not None:
            self._array = self._array.reshape(shape)


class Predictor:
    def __init__(self, config: Config):
        from ..jit import load as jit_load

        self._config = config
        self._layer = jit_load(config.model_path())
        if self._layer._exported is None:
            raise ValueError(
                f"{config.model_path()!r} has no serialized program; re-save the "
                "model with paddle.jit.save(layer, path, input_spec=[...])")
        n_in = self._layer._exported.in_avals
        # first tree arg is the weights dict; the rest are user inputs
        import jax

        treedef = self._layer._exported.in_tree
        args_structure = jax.tree_util.treedef_children(treedef)[0]
        n_user = len(jax.tree_util.treedef_children(args_structure)) - 1
        self._inputs = [_Handle() for _ in range(n_user)]
        self._outputs: list[_Handle] = []
        self._device = config._device

    # ------------------------------------------------------------- handle API
    def get_input_names(self):
        return [f"x{i}" for i in range(len(self._inputs))]

    def get_input_handle(self, name):
        return self._inputs[int(name[1:]) if name.startswith("x") else int(name)]

    def get_output_names(self):
        return [f"out{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name):
        return self._outputs[int(name[3:]) if name.startswith("out") else int(name)]

    # ------------------------------------------------------------- execution
    def run(self, inputs=None):
        """Either positional-arrays in / arrays out, or the handle protocol:
        copy_from_cpu → run() → copy_to_cpu."""
        import jax

        if inputs is not None:
            arrays = [np.asarray(x) for x in inputs]
        else:
            arrays = [h._array for h in self._inputs]
            if any(a is None for a in arrays):
                raise ValueError("input handles not filled; call copy_from_cpu first")
        out = self._layer.forward(*arrays)
        flat = jax.tree_util.tree_leaves(out)
        results = [np.asarray(t._value if hasattr(t, "_value") else t) for t in flat]
        self._outputs = []
        for r in results:
            h = _Handle()
            h.copy_from_cpu(r)
            self._outputs.append(h)
        return results


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


from .kv_cache import (  # noqa: E402,F401  (serving-layer paged KV cache)
    BlockAllocator,
    CacheOutOfBlocks,
    PagedKVCache,
)

from .speculative import (  # noqa: E402,F401  (draft/verify decoding)
    Drafter,
    DraftModelDrafter,
    NGramDrafter,
    SelfSpeculativeDrafter,
    SpecStats,
    make_drafter,
    speculative_generate,
)

from .qos import (  # noqa: E402,F401  (multi-tenant QoS + fleet autoscaling)
    FleetAutoscaler,
    TenantLedger,
    TenantSpec,
)
