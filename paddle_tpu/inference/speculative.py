"""Speculative decoding: pluggable drafters + the single-stream driver.

Single-stream decode is the serving shape that wastes the chip: each step
launches one token of work, so b1 runs at dispatch speed, not math speed
(~625 vs ~3.5k tok/s — docs/PERF.md). Speculative decoding (Leviathan et
al., "Fast Inference from Transformers via Speculative Decoding", ICML
2023; Stern et al., NeurIPS 2018) converts the idle width into useful
tokens: a cheap DRAFTER proposes K tokens, and the target model scores all
K in ONE forward (`GenerationMixin.verify_step`, a prefill_chunk-shaped
call over the split-KV paged attention) that also runs the accept/reject
sampler in-program. Accepted tokens are free; the rejection resample is
corrected so the output distribution is EXACTLY the target model's —
greedy speculative output is token-identical to dense `generate()`
(pinned in tests/test_speculative.py).

Drafters implement one method and are deliberately dumb-simple:

    draft(history, k) -> up to k proposed continuation tokens (np.ndarray)

They must be DETERMINISTIC (a point-mass draft distribution): that is the
condition under which verify_step's acceptance test p(d_j) and masked-
residual resample are exact (min(1, p/q) with q a point mass is p(d),
and max(p - q, 0) renormalized is p with d removed). A stochastic draft
model would need its per-token proposal probabilities threaded into the
verify program; the `Drafter` protocol is where that hook would land.

Shipped drafters:

* ``NGramDrafter`` — prompt-lookup decoding: find the most recent earlier
  occurrence of the longest suffix n-gram of the history and propose the
  tokens that followed it. Host-only, model-free, zero launches; shines on
  self-repetitive text (code, summaries quoting their source, chat with
  retrieval) and degrades to acceptance ~0 (never below plain decode
  throughput-per-launch) on incompressible text.
* ``DraftModelDrafter`` — the draft-model hook point: greedy proposals
  from ANY model exposing the GenerationMixin `generate()` interface,
  drafting from a FIXED-width suffix window so the draft program compiles
  once per (window, k) and never again.
* ``SelfSpeculativeDrafter`` — shallow-prefix reuse of the TARGET model:
  DraftModelDrafter with draft_model == target. The draft only attends the
  last `window` tokens, so a draft launch costs O(window) attention
  instead of O(full prefix) — profitable once the accepted-token value
  beats the extra small launches (cost model in docs/PERF.md).

The continuous scheduler (scheduler.py, ``spec_k=`` knob) drives the same
verify program at S slots; this module's `speculative_generate` is the
single-stream (S=1) driver behind `model.generate_speculative(...)`.
"""
from __future__ import annotations

import itertools

import numpy as np

from .kv_cache import PagedKVCache

__all__ = ["Drafter", "NGramDrafter", "DraftModelDrafter",
           "SelfSpeculativeDrafter", "make_drafter", "SpecStats",
           "speculative_generate"]


class Drafter:
    """Protocol for draft-token proposers (duck-typed; subclassing is
    optional — anything with this method works).

    ``history`` is the full 1-D int sequence so far (prompt + generated);
    return up to ``k`` proposed continuation tokens as a 1-D array (empty
    = no proposal, the driver degrades to plain one-token decode through
    the same compiled program). Proposals must be deterministic given
    `history` — see the module docstring for why."""

    def draft(self, history: np.ndarray, k: int) -> np.ndarray:
        raise NotImplementedError


class NGramDrafter(Drafter):
    """Prompt-lookup drafter: longest-suffix n-gram match against the
    sequence's own past, proposing the tokens that followed the match.

    max_n..min_n are tried longest-first; the most RECENT earlier match
    wins (recent context predicts better than distant context). O(L * n)
    host work per draft — microseconds at serving lengths, and exactly
    zero device launches."""

    def __init__(self, max_n: int = 3, min_n: int = 1):
        if not 1 <= min_n <= max_n:
            raise ValueError(f"need 1 <= min_n <= max_n, got "
                             f"({min_n}, {max_n})")
        self.max_n = int(max_n)
        self.min_n = int(min_n)

    def draft(self, history, k):
        h = np.asarray(history).reshape(-1)
        L = len(h)
        k = int(k)
        if k < 1:
            return h[:0]
        for n in range(min(self.max_n, L - 1), self.min_n - 1, -1):
            pat = h[L - n:]
            # latest occurrence strictly before the suffix itself, with at
            # least one continuation token available
            for i in range(L - n - 1, -1, -1):
                if np.array_equal(h[i:i + n], pat):
                    return h[i + n:i + n + k]
        return h[:0]


class DraftModelDrafter(Drafter):
    """Draft-model hook point: greedy proposals from any GenerationMixin
    model, conditioned on a FIXED-width suffix window of the history.

    The fixed window is the recompile discipline: the draft program's
    shape is (1, window) + k new tokens, compiled once. Histories shorter
    than the window propose nothing (the driver plain-decodes those early
    tokens) rather than compiling a program per prompt length. `k_fixed`
    pins the drafted width too — the driver may ask for fewer near a
    sequence's budget and truncates host-side, so the tail of a sequence
    never forks a narrower draft program."""

    def __init__(self, draft_model, window: int = 16, k_fixed: int | None
                 = None, dtype="bfloat16", decode_kernel=None):
        self.model = draft_model
        self.window = int(window)
        self.k_fixed = None if k_fixed is None else int(k_fixed)
        self.dtype = dtype
        self.decode_kernel = decode_kernel

    def draft(self, history, k):
        h = np.asarray(history).reshape(-1)
        k = int(k)
        if k < 1 or len(h) < self.window:
            return h[:0]
        kk = self.k_fixed if self.k_fixed is not None else k
        if kk < k:
            k = kk
        ctx = np.asarray(h[-self.window:], np.int64)[None]
        out = self.model.generate(
            ctx, max_new_tokens=kk, temperature=0.0, dtype=self.dtype,
            decode_kernel=self.decode_kernel)
        out = np.asarray(out._value if hasattr(out, "_value") else out)
        return out[0, self.window:self.window + k]


class SelfSpeculativeDrafter(DraftModelDrafter):
    """Self-speculation (shallow-prefix reuse): the TARGET model drafts
    its own continuation from a short suffix window. No second model to
    deploy; the draft is cheap because it attends `window` tokens, not the
    full prefix — and wrong exactly where truncated context misleads,
    which the verify step then charges as rejections."""

    def __init__(self, model, window: int = 16, k_fixed: int | None = None,
                 dtype="bfloat16", decode_kernel=None):
        super().__init__(model, window=window, k_fixed=k_fixed, dtype=dtype,
                         decode_kernel=decode_kernel)


def make_drafter(spec, model=None) -> Drafter:
    """Resolve a drafter knob: 'ngram' | 'self' | a Drafter instance."""
    if spec is None:
        return NGramDrafter()
    if isinstance(spec, str):
        if spec == "ngram":
            return NGramDrafter()
        if spec == "self":
            if model is None:
                raise ValueError("drafter='self' needs the target model")
            return SelfSpeculativeDrafter(model)
        raise ValueError(f"unknown drafter {spec!r} "
                         "(expected 'ngram', 'self', or a Drafter)")
    if hasattr(spec, "draft"):
        return spec
    raise ValueError(f"not a drafter: {spec!r} (needs .draft(history, k))")


class SpecStats:
    """Per-run speculation accounting. wasted = drafted - accepted is the
    draft compute (and verify width) spent on rejected tokens; the
    acceptance rate is THE number that decides whether speculation pays
    (docs/PERF.md cost model)."""

    __slots__ = ("drafted", "accepted", "launches", "emitted")

    def __init__(self):
        self.drafted = 0        # draft tokens submitted to verify
        self.accepted = 0       # draft tokens accepted by the target
        self.launches = 0       # verify launches (each also emits 1 token)
        self.emitted = 0        # total tokens produced (accepted + emitted)

    @property
    def wasted(self) -> int:
        return self.drafted - self.accepted

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.drafted if self.drafted else 0.0

    def to_dict(self) -> dict:
        return {"drafted": self.drafted, "accepted": self.accepted,
                "wasted": self.wasted, "launches": self.launches,
                "emitted": self.emitted,
                "acceptance_rate": round(self.acceptance_rate, 4)}

    def unit_split(self, width) -> tuple[int, int, int]:
        """(useful, spec_waste, pad) verify-row units for the utilization
        ledger (ISSUE-19), out of ``launches * width`` total rows: every
        emitted token was a useful row, every rejected draft was a
        spec-waste row, the rest of each fixed-width launch was padding.
        Same convention as the scheduler's per-tick attribution, so a
        single-stream speculative run decomposes its FLOPs identically."""
        total = self.launches * int(width)
        useful = min(self.emitted, total)
        spec = min(self.wasted, total - useful)
        return useful, spec, total - useful - spec

    def __repr__(self):
        return f"SpecStats({self.to_dict()})"


_RID = itertools.count(1)   # process-unique reservation ids (atomic draw)


def speculative_generate(model, input_ids, max_new_tokens=32, spec_k=4,
                         drafter="ngram", temperature=0.0, top_k=0,
                         eos_token_id=None, seed=0, dtype="bfloat16",
                         decode_kernel="pallas", kv_cache=None, stats=None,
                         timing_hook=None):
    """Single-stream draft/verify decode loop (the b1 fast path).

    Semantics match `generate()`: returns prompt + max_new_tokens ids
    (same leading shape as the input), EOS freezes the remainder, greedy
    output is token-identical to the dense scan. Mechanics: prefill the
    prompt in one `prefill_chunk` launch, then per iteration draft up to
    `spec_k` tokens on the host and score/accept them in one
    `verify_step` launch (1 + accepted tokens per launch; a draft drought
    degrades to 1 token/launch through the SAME compiled program).

    `kv_cache`: optional shared PagedKVCache; by default a private pool
    sized for this request is used. `stats`: optional SpecStats
    accumulated in place (acceptance-rate observability).
    """
    ids = np.asarray(input_ids._value if hasattr(input_ids, "_value")
                     else input_ids)
    batched = ids.ndim == 2
    if batched and ids.shape[0] != 1:
        raise ValueError("speculative_generate is the single-stream path "
                         f"(got batch {ids.shape[0]}); batched service goes "
                         "through the continuous scheduler's spec_k knob")
    flat = ids.reshape(-1).astype(np.int64)
    plen = len(flat)
    max_new = int(max_new_tokens)
    K = int(spec_k)
    if K < 1:
        raise ValueError("spec_k must be >= 1")
    model._decode_validate(plen, max_new)
    d = make_drafter(drafter, model)
    st = stats if stats is not None else SpecStats()
    eos = None if eos_token_id is None else int(eos_token_id)
    seed_iter = itertools.count(int(seed))

    total = plen + max_new
    own_pool = kv_cache is None
    if own_pool:
        spec_l, spec_h, spec_d = model._decode_cache_spec()
        bs = 32
        kv_cache = PagedKVCache(
            spec_l, spec_h, spec_d, block_size=bs,
            num_blocks=(total + bs - 1) // bs + 1,
            dtype="float32" if dtype is None else dtype)
    rid = ("spec", next(_RID))
    kv_cache.reserve(rid, total)
    nb = kv_cache.blocks_for(total)
    table = np.asarray(kv_cache.block_table(rid, pad_to=nb),
                       np.int32)[None]

    generated: list[int] = []
    done = False

    def absorb(toks):
        nonlocal done
        for t in toks:
            if len(generated) >= max_new:
                break
            t = int(t)
            generated.append(t)
            if eos is not None and t == eos:
                generated.extend([eos] * (max_new - len(generated)))
                done = True
                break
        if len(generated) >= max_new:
            done = True

    try:
        tok = model.prefill_chunk(
            flat[None], np.zeros(1, np.int64), np.asarray([plen], np.int64),
            kv_cache, table, temperature=temperature, top_k=top_k,
            eos_token_id=eos_token_id, seed=next(seed_iter),
            decode_kernel=decode_kernel, timing_hook=timing_hook)
        cur = int(np.asarray(tok._value if hasattr(tok, "_value")
                             else tok)[0])
        kv_cache.append_tokens(rid, plen)
        length = plen
        absorb([cur])

        chunk = np.zeros((1, K + 1), np.int64)
        while not done:
            history = np.concatenate([flat, np.asarray(generated, np.int64)])
            remaining = max_new - len(generated)
            proposal = np.asarray(d.draft(history, K),
                                  np.int64).reshape(-1)[:K]
            dlen = min(len(proposal), remaining - 1)
            chunk[:] = 0
            chunk[0, 0] = cur
            if dlen > 0:
                chunk[0, 1:1 + dlen] = proposal[:dlen]
            acc, nxt = model.verify_step(
                chunk, np.asarray([length], np.int64),
                np.asarray([dlen], np.int64), np.asarray([True]),
                kv_cache, table, max_lens=np.asarray([total], np.int64),
                temperature=temperature, top_k=top_k, seed=next(seed_iter),
                decode_kernel=decode_kernel, timing_hook=timing_hook)
            a = int(np.asarray(acc._value if hasattr(acc, "_value")
                               else acc)[0])
            nx = int(np.asarray(nxt._value if hasattr(nxt, "_value")
                                else nxt)[0])
            st.drafted += dlen
            st.accepted += a
            st.launches += 1
            # rollback by bookkeeping: only the accepted prefix + the
            # emitted token become committed rows; rejected rows get
            # overwritten by the next launch's full-width write window
            length += 1 + a
            try:
                kv_cache.append_tokens(rid, 1 + a)
            except (KeyError, ValueError):  # pragma: no cover - audit-only
                pass
            cur = nx
            absorb([int(t) for t in chunk[0, 1:1 + a]] + [nx])
        st.emitted += len(generated)
    finally:
        try:
            kv_cache.mark_done(rid)
            kv_cache.release(rid)
        except KeyError:    # pragma: no cover - already released
            pass

    out = np.concatenate([flat, np.asarray(generated, np.int64)])
    out = out.astype(ids.dtype)
    return out[None] if batched else out
