"""Stateless RNG with a stateful facade.

Reference parity: paddle.seed / paddle.get_rng_state; fleet's `RNGStatesTracker`
(python/paddle/distributed/fleet/layers/mpu/random.py:34 in the reference) keeps distinct
dropout streams across tensor-parallel ranks. TPU-native design: a single jax PRNG key plus
a split counter. Every random op folds the counter into the key — pure data flow, no device
state, reproducible under jit (the counter is captured at trace time per call site).
"""
from __future__ import annotations

import contextlib

import jax
import numpy as np


class Generator:
    """A stateful wrapper over a jax PRNG key chain."""

    def __init__(self, seed: int = 0):
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        # The key materializes lazily: creating it eagerly would initialize the
        # XLA backend at `import paddle_tpu`, which breaks
        # jax.distributed.initialize (must run before any backend init).
        self._key = None
        self._counter = 0
        return self

    def base_key(self):
        """The stream's base PRNG key, materialized lazily (see manual_seed).
        A pure function of ``_seed`` — callers folding per-step values into
        it (TrainStep) stay reproducible across ``set_state`` round-trips."""
        if self._key is None:
            self._key = jax.random.key(self._seed)
        return self._key

    def next_key(self):
        """Return a fresh key; advances the stream. Under a TrainStep trace a traced
        base key is folded in instead of the host key, so compiled steps get fresh
        randomness per call rather than a baked-in constant."""
        global _consume_count
        _consume_count += 1  # dispatch cache: randomness makes an op uncacheable
        base = _trace_key if _trace_key is not None else self.base_key()
        k = jax.random.fold_in(base, self._counter)
        self._counter += 1
        return k

    def get_state(self):
        return (self._seed, self._counter)

    def set_state(self, state):
        self._seed, self._counter = state
        self._key = None
        return self


_default_generator = Generator(np.random.randint(0, 2**31 - 1))
_trace_key = None
_consume_count = 0  # bumped by every next_key(); see ops.apply_op's cache


@contextlib.contextmanager
def trace_key(key):
    """Route random ops through a traced base key (used by compiled train steps)."""
    global _trace_key
    prev = _trace_key
    _trace_key = key
    try:
        yield
    finally:
        _trace_key = prev


def seed(s: int) -> Generator:
    """paddle.seed"""
    return _default_generator.manual_seed(s)


def default_generator() -> Generator:
    return _default_generator


def next_key():
    return _default_generator.next_key()


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)


class RNGStatesTracker:
    """Named RNG streams (reference: mpu/random.py RNGStatesTracker).

    Used by tensor parallelism: 'global_seed' stream is identical across TP ranks
    (e.g. for residual dropout), 'local_seed' differs per rank (weight init / dropout on
    sharded activations). Streams are independent Generators.
    """

    def __init__(self):
        self._states: dict[str, Generator] = {}

    def reset(self):
        self._states = {}

    def add(self, name: str, s: int):
        if name in self._states:
            raise ValueError(f"state {name!r} already exists")
        self._states[name] = Generator(s)

    def get_states_tracker(self):
        return {k: g.get_state() for k, g in self._states.items()}

    def set_states_tracker(self, states):
        self._states = {k: Generator(0).set_state(v) for k, v in states.items()}

    @contextlib.contextmanager
    def rng_state(self, name: str = "global_seed"):
        """Temporarily make the named stream the default generator."""
        global _default_generator
        if name not in self._states:
            raise ValueError(f"state {name!r} not added yet")
        prev = _default_generator
        _default_generator = self._states[name]
        try:
            yield
        finally:
            _default_generator = prev


_rng_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _rng_tracker


def model_parallel_random_seed(seed_: int, tp_rank: int = 0):
    """Reference: mpu/random.py model_parallel_random_seed — set up global/local streams."""
    global_seed = 100003 + seed_
    local_seed = seed_ + 1024 + tp_rank * 100
    _rng_tracker.reset()
    _rng_tracker.add("global_seed", global_seed)
    _rng_tracker.add("local_seed", local_seed)
    seed(global_seed)
