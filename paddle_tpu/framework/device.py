"""Device / place abstraction.

Reference parity: paddle.CUDAPlace / CPUPlace / set_device ("gpu:0") — here the accelerator
is whatever jax exposes (TPU on real hardware, CPU in CI). A "place" wraps a jax.Device.
There is no per-op device dispatch: XLA owns placement; `to(device)` is `jax.device_put`.
"""
from __future__ import annotations

import jax


class Place:
    """A device handle. Compares by (platform, index)."""

    def __init__(self, device: "jax.Device | None" = None):
        self._device = device if device is not None else jax.devices()[0]

    @property
    def device(self):
        return self._device

    @property
    def platform(self) -> str:
        return self._device.platform

    def get_device_id(self) -> int:
        return self._device.id

    def is_gpu_place(self) -> bool:
        return self._device.platform == "gpu"

    def is_cpu_place(self) -> bool:
        return self._device.platform == "cpu"

    def is_tpu_place(self) -> bool:
        return self._device.platform not in ("cpu", "gpu")

    def __eq__(self, other):
        return isinstance(other, Place) and other._device == self._device

    def __hash__(self):
        return hash(self._device)

    def __repr__(self):
        return f"Place({self._device.platform}:{self._device.id})"


class CPUPlace(Place):
    def __init__(self):
        cpus = [d for d in jax.devices("cpu")] if _has_platform("cpu") else jax.devices()
        super().__init__(cpus[0])


class TPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__(jax.devices()[device_id])


# Alias so scripts written for the reference's `CUDAPlace(0)` keep running on the accelerator.
CUDAPlace = TPUPlace
XPUPlace = TPUPlace
CustomPlace = TPUPlace


def _has_platform(name: str) -> bool:
    try:
        jax.devices(name)
        return True
    except RuntimeError:
        return False


_current_device: Place | None = None


def set_device(device) -> Place:
    """paddle.device.set_device — accepts 'cpu', 'tpu', 'tpu:0', 'gpu:0' (alias), a Place."""
    global _current_device
    if isinstance(device, Place):
        _current_device = device
        return _current_device
    name = str(device)
    if ":" in name:
        plat, _, idx = name.partition(":")
        idx = int(idx)
    else:
        plat, idx = name, 0
    if plat == "cpu":
        _current_device = CPUPlace()
    else:
        devs = jax.devices()
        _current_device = Place(devs[min(idx, len(devs) - 1)])
    return _current_device


def get_device() -> str:
    p = get_place()
    return f"{p.platform}:{p.get_device_id()}"


def get_place() -> Place:
    global _current_device
    if _current_device is None:
        _current_device = Place(jax.devices()[0])
    return _current_device


def device_count() -> int:
    return len(jax.devices())


def is_compiled_with_cuda() -> bool:  # reference API; always False on the TPU build
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return any(d.platform not in ("cpu", "gpu") for d in jax.devices())
