"""Dtype system.

Reference parity: paddle exposes dtypes as `paddle.float32`, `paddle.int64`, ... and a
`get_default_dtype`/`set_default_dtype` pair (python/paddle/framework/framework.py in the
reference). Here dtypes ARE numpy/jax dtypes — no custom enum: XLA is the only backend, so
jnp dtypes are the native currency and everything interops with numpy for free.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects (jax dtypes). bfloat16 is the TPU-native half type.
bool_ = jnp.bool_.dtype if hasattr(jnp.bool_, "dtype") else np.dtype(bool)
uint8 = np.dtype("uint8")
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
float16 = np.dtype("float16")
bfloat16 = jnp.bfloat16.dtype if hasattr(jnp.bfloat16, "dtype") else jnp.dtype(jnp.bfloat16)
float32 = np.dtype("float32")
float64 = np.dtype("float64")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")
float8_e4m3fn = jnp.float8_e4m3fn.dtype if hasattr(jnp, "float8_e4m3fn") else None
float8_e5m2 = jnp.float8_e5m2.dtype if hasattr(jnp, "float8_e5m2") else None

_STR_ALIASES = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    "fp16": float16,
    "fp32": float32,
    "fp64": float64,
}


def convert_dtype(dtype):
    """Normalize any user-facing dtype spec (str, np.dtype, jnp type) to an np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower()
        if key in _STR_ALIASES:
            return _STR_ALIASES[key]
        return np.dtype(dtype)
    if isinstance(dtype, np.dtype):
        return dtype
    # jnp scalar types (jnp.float32 etc.) and python builtins
    try:
        return jnp.dtype(dtype)
    except TypeError:
        return np.dtype(dtype)


def dtype_to_str(dtype) -> str:
    d = convert_dtype(dtype)
    return d.name if d is not None else "None"


_default_dtype = float32


def set_default_dtype(d):
    """paddle.set_default_dtype — only floating point types are legal (matches reference)."""
    global _default_dtype
    d = convert_dtype(d)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(
            "set_default_dtype only supports float16/bfloat16/float32/float64, got %s" % d
        )
    _default_dtype = d


def get_default_dtype():
    return _default_dtype


def is_floating_dtype(dtype) -> bool:
    d = convert_dtype(dtype)
    return jnp.issubdtype(d, jnp.floating)


def is_integer_dtype(dtype) -> bool:
    d = convert_dtype(dtype)
    return jnp.issubdtype(d, jnp.integer)


def is_complex_dtype(dtype) -> bool:
    d = convert_dtype(dtype)
    return jnp.issubdtype(d, jnp.complexfloating)


def finfo(dtype):
    return jnp.finfo(convert_dtype(dtype))


def iinfo(dtype):
    return jnp.iinfo(convert_dtype(dtype))
