"""Preemption-tolerant training checkpoints: async, sharded, bit-exact.

Production TPU fleets get preempted; a training run must treat that as
routine (ROADMAP open item 5). This module owns the on-disk checkpoint
lifecycle for full ``jit/train.py:TrainStep`` state — params, optimizer
moments, step counter, RNG state, monitor counters — with three properties
the simpler ``io_utils.save`` path cannot give:

1. **Asynchrony.** ``save()`` splits into three phases. *snapshot* runs on
   the caller thread right after a step: device→host transfers are kicked
   off for every array at once (``copy_to_host_async``) and materialized
   into a host tree — this MUST finish before the next step launches,
   because TrainStep donates its state buffers and a later read would find
   them deleted. *serialize* (npz write + fsync) and *commit* (manifest +
   atomic rename + retention) then run on a background writer thread,
   overlapped with the next steps' compute. Only the snapshot cost lands on
   the training loop; bench.py's ``checkpoint_overhead`` leg gates it < 2%
   of the GPT-smoke step time.

2. **Crash-atomicity.** Each checkpoint is a step-numbered directory,
   assembled under a ``.tmp`` name and renamed into place only after every
   data file is fsynced and the manifest — written last, itself via
   tmp+rename — records each file's size and crc32. A kill at ANY point
   leaves either a complete checkpoint or ignorable debris; ``restore()``
   walks manifests newest-first, verifies integrity, and falls back to the
   previous intact checkpoint on corruption with a typed
   ``CheckpointCorruptWarning`` — it never crashes on torn state.

3. **Sharding.** Every process writes only its own replica-0 shards
   (``data_r{rank}.npz``, the ``distributed/checkpoint`` chunk format); the
   coordinator collates per-rank sidecars into the manifest. Restore is
   mesh-aware: chunks are stitched through ``ChunkReader`` against each
   array's CURRENT sharding, so a run can resume on a different process
   count than it saved with (shared-filesystem checkpoints, the TPU-pod
   norm).

Fault drills: with an ``inference/faults.py`` injector attached, the sites
``ckpt.snapshot`` / ``ckpt.serialize`` / ``ckpt.commit`` are checked at each
phase entry and all timing reads go through the injector's skewable clock —
the kill/resume suite in tests/test_checkpoint.py is deterministic, not
probabilistic. Goodput accounting rides the bound ``StepMonitor``
(``paddle_train_goodput``, ``paddle_train_checkpoint_seconds{phase}``,
``paddle_train_checkpoints_total``); recipes in docs/DEPLOYMENT.md
("Preemption & resume") and docs/OBSERVABILITY.md.
"""
from __future__ import annotations

import json
import os
import queue
import shutil
import threading
import time
import warnings
import zlib

import numpy as np

from ..analysis.lockwitness import make_lock
from .io_utils import fsync_dir, fsync_file

__all__ = ["CheckpointManager", "CheckpointCorruptWarning", "latest_step",
           "PreemptionFlush", "PreemptionExit"]

_MANIFEST = "manifest.json"
_STEP_PREFIX = "step_"
_TMP_SUFFIX = ".tmp"


class CheckpointCorruptWarning(UserWarning):
    """A checkpoint directory failed integrity validation (torn manifest,
    missing/truncated/corrupt shard). The manager falls back to the previous
    intact checkpoint instead of crashing — but the operator should know."""


class PreemptionExit(SystemExit):
    """Raised by the training loop after a SIGTERM-triggered final flush.

    Subclasses SystemExit carrying ``ELASTIC_EXIT_CODE`` (101), so an
    un-caught preemption exits the worker process with the code the elastic
    launch controller treats as "restart me, this is not a crash" — the
    same contract the legacy ``AutoCheckpointer`` spoke, now available to
    every ``CheckpointManager``-checkpointed fit loop."""


class PreemptionFlush:
    """SIGTERM -> flag; the training loop polls and flushes synchronously.

    Pod preemption lands as SIGTERM with a grace window (the elastic launch
    controller's ``stop_pod`` sends exactly that). The handler itself must
    not serialize state — the signal can land mid-optimizer-update — so it
    only sets ``preempted``; the fit loop checks the flag at the next batch
    boundary, takes a final SYNCHRONOUS ``CheckpointManager.save`` of
    well-formed post-step state, and raises :class:`PreemptionExit`.

    ``install()`` is a no-op outside the main thread (Python only delivers
    signals there) and chains nothing: the previous handler is restored by
    ``restore()`` in the fit loop's ``finally``."""

    def __init__(self):
        self.preempted = False
        self.installed = False
        self._prev = None

    def install(self) -> "PreemptionFlush":
        import signal

        try:
            self._prev = signal.signal(signal.SIGTERM, self._on_sigterm)
            self.installed = True
        except ValueError:      # not the main thread: poll-only mode
            self.installed = False
        return self

    def _on_sigterm(self, signum, frame):
        self.preempted = True

    def restore(self):
        if not self.installed:
            return
        import signal

        signal.signal(signal.SIGTERM, self._prev or signal.SIG_DFL)
        self.installed = False

    @staticmethod
    def exit_code() -> int:
        from ..distributed.fleet.elastic.manager import ELASTIC_EXIT_CODE

        return ELASTIC_EXIT_CODE


def _step_dirname(step):
    return f"{_STEP_PREFIX}{int(step):010d}"


def _parse_step(name):
    if not name.startswith(_STEP_PREFIX) or name.endswith(_TMP_SUFFIX):
        return None
    try:
        return int(name[len(_STEP_PREFIX):])
    except ValueError:
        return None


def latest_step(directory):
    """Highest step number with a manifest present (cheap discovery; full
    integrity validation happens in ``restore``). None when none exist."""
    best = None
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    for name in names:
        step = _parse_step(name)
        if step is None:
            continue
        if not os.path.exists(os.path.join(directory, name, _MANIFEST)):
            continue
        if best is None or step > best:
            best = step
    return best


def _flatten(tree, prefix=""):
    flat = {}
    for k, v in tree.items():
        name = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten(v, prefix=f"{name}."))
        else:
            flat[name] = v
    return flat


def _crc_file(path, chunk=1 << 20):
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
    return crc & 0xFFFFFFFF


class _CorruptCheckpoint(Exception):
    """Internal: validation failure reason (becomes the warning message)."""


class CheckpointManager:
    """Async sharded save / mesh-aware restore of TrainStep training state.

    Usage (bare loop)::

        mgr = CheckpointManager(ckpt_dir, keep_last=3, keep_every=100)
        start = mgr.restore(step) or 0          # step = TrainStep(...)
        for i in range(start, total):
            loss = step(x, labels=y)
            if (i + 1) % save_every == 0:
                mgr.save(step, i + 1)           # snapshot now, write async
        mgr.save(step, total)
        mgr.close()                             # drain pending writes

    ``Model.fit(checkpoint_dir=...)`` wires this up automatically.

    The state provider contract is two methods: ``export_state()`` returning
    ``{"params": {...}, "acc": {...}, ["master": {...}], "meta": {...}}``
    with array leaves (jax or numpy) and a JSON-able ``meta``, and
    ``import_state(state)`` accepting the same shape back with numpy/jax
    leaves. ``jit/train.py:TrainStep`` implements it; anything else (an
    eager loop's shuttle object) can too.

    * ``keep_last`` — newest N checkpoints retained (0/None = keep all).
    * ``keep_every`` — additionally retain every checkpoint whose step is a
      multiple of M (milestones survive the sliding window).
    * ``async_save`` — False serializes+commits on the caller thread
      (useful under test and for a final synchronous flush).
    * ``monitor`` — a ``StepMonitor``; phase timings feed
      ``paddle_train_checkpoint_seconds{phase}`` and commit/restore feed the
      goodput window. Reassignable at any time (fit binds it lazily).
    * ``injector`` — ``inference/faults.py:FaultInjector`` for deterministic
      kill/skew drills at the ``ckpt.*`` sites.
    """

    def __init__(self, directory, *, keep_last=3, keep_every=0,
                 async_save=True, rank=None, world_size=None, monitor=None,
                 injector=None):
        self.directory = str(directory)
        self.keep_last = None if not keep_last else int(keep_last)
        self.keep_every = int(keep_every or 0)
        self.async_save = bool(async_save)
        if rank is None or world_size is None:
            try:
                from ..distributed.env import get_rank, get_world_size

                rank = get_rank() if rank is None else rank
                world_size = (get_world_size() if world_size is None
                              else world_size)
            except Exception:
                rank, world_size = rank or 0, world_size or 1
        self.rank = int(rank)
        self.world_size = max(1, int(world_size))
        self.monitor = monitor
        self.injector = injector
        self.last_timings: dict = {}   # phase -> seconds, last finished save
        self.saves = 0                 # snapshots taken
        self.commits = 0               # manifests landed (this process)
        self.last_restored = None      # {"step", "dir", "meta"} after restore
        self._q: queue.Queue = queue.Queue(maxsize=1)
        self._writer = None
        self._writer_err = None
        # one lock guards writer lifecycle AND the cross-thread scalars
        # (saves/commits/last_timings/_writer_err) — thread-lint discipline
        self._lock = make_lock("checkpoint.CheckpointManager._lock")
        os.makedirs(self.directory, exist_ok=True)

    # ----------------------------------------------------------------- clock
    def _now(self):
        inj = self.injector
        return inj.monotonic() if inj is not None else time.monotonic()

    def _check(self, site):
        inj = self.injector
        if inj is not None:
            inj.check(site)

    def _phase(self, phase, seconds):
        with self._lock:    # caller thread (snapshot) and writer both land
            self.last_timings[phase] = seconds
        mon = self.monitor  # monitor has its own locking; call outside ours
        if mon is not None:
            mon.checkpoint_phase(phase, seconds)

    # ------------------------------------------------------------------ save
    def save(self, provider, step, blocking=None):
        """Snapshot `provider` state at optimizer-step `step` and hand it to
        the writer. Returns the final checkpoint directory path (which exists
        only after the async commit lands — ``wait()`` to join)."""
        self._raise_writer_error()
        t0 = self._now()
        self._check("ckpt.snapshot")
        snap = provider.export_state()
        chunks, entries = self._snapshot(snap)
        meta = dict(snap.get("meta") or {})
        self._phase("snapshot", self._now() - t0)
        with self._lock:
            self.saves += 1
        job = {"step": int(step), "chunks": chunks, "entries": entries,
               "meta": meta}
        if blocking is None:
            blocking = not self.async_save
        if blocking:
            self._write(job)
        else:
            self._ensure_writer()
            self._q.put(job)   # maxsize=1: a third save blocks until the
            # in-flight write drains (bounds host memory to 2 snapshots)
        return os.path.join(self.directory, _step_dirname(step))

    def _snapshot(self, snap):
        """Host-materialize every array leaf into per-rank chunk arrays +
        manifest entries. Transfers for ALL arrays are kicked off before the
        first blocking read so D2H pipelines; the result is pure numpy — safe
        against the next step donating the device buffers."""
        import jax

        from ..distributed.checkpoint import _index_to_offsets, storable_view

        flat = {k: v for k, v in _flatten(snap).items()
                if not k.startswith("meta.")}
        for v in flat.values():
            if isinstance(v, jax.Array) and hasattr(v, "copy_to_host_async"):
                try:
                    v.copy_to_host_async()
                except Exception:   # pragma: no cover - backend-specific
                    pass
        chunks, entries = {}, {}
        for name, v in flat.items():
            if v is None or isinstance(v, (int, float, str, bool)):
                entries[name] = {"kind": "scalar", "value": v}
                continue
            if isinstance(v, jax.Array) and len(
                    getattr(v, "sharding", None).device_set
                    if getattr(v, "sharding", None) is not None else ()) > 1:
                entry = {"kind": "tensor", "shape": list(v.shape),
                         "dtype": str(np.dtype(v.dtype)), "chunks": []}
                seen = set()
                for shard in v.addressable_shards:
                    if shard.replica_id != 0:
                        continue   # exactly one replica saves each region
                    offset, cshape = _index_to_offsets(shard.index, v.shape)
                    if tuple(offset) in seen:
                        continue
                    seen.add(tuple(offset))
                    cname = f"{name}/{len(entry['chunks'])}"
                    chunks[cname] = storable_view(np.asarray(shard.data))
                    entry["chunks"].append(
                        {"offset": offset, "shape": cshape,
                         "file": self._data_name(), "key": cname})
                entries[name] = entry
                continue
            arr = np.asarray(v)
            entries[name] = {"kind": "tensor", "shape": list(arr.shape),
                             "dtype": str(arr.dtype), "chunks": []}
            if self.rank == 0:   # replicated single-device value: rank 0 owns
                cname = f"{name}/0"
                chunks[cname] = storable_view(arr)
                entries[name]["chunks"].append(
                    {"offset": [0] * arr.ndim, "shape": list(arr.shape),
                     "file": self._data_name(), "key": cname})
        return chunks, entries

    def _data_name(self):
        return f"data_r{self.rank}.npz"

    # ---------------------------------------------------------- writer thread
    def _ensure_writer(self):
        with self._lock:
            if self._writer is not None and self._writer.is_alive():
                return
            self._writer = threading.Thread(
                target=self._writer_loop, name="ckpt-writer", daemon=True)
            self._writer.start()

    def _writer_loop(self):
        while True:
            job = self._q.get()
            if job is None:
                self._q.task_done()   # keep q.join() sound after close()
                return
            try:
                self._write(job)
            except BaseException as e:   # surfaced on next save()/wait()
                with self._lock:
                    self._writer_err = e
            finally:
                self._q.task_done()

    def _raise_writer_error(self):
        with self._lock:
            err, self._writer_err = self._writer_err, None
        if err is not None:
            mon = self.monitor
            if mon is not None:
                mon.checkpoint_result(ok=False)
            raise RuntimeError(
                f"async checkpoint write failed: {err!r}") from err

    def wait(self, timeout=None):
        """Join all pending async writes; re-raises a writer failure."""
        if self._writer is not None and self._writer.is_alive():
            self._q.join()
        self._raise_writer_error()

    def close(self):
        """Drain pending writes and stop the writer thread."""
        self.wait()
        with self._lock:
            w, self._writer = self._writer, None
        if w is not None and w.is_alive():
            self._q.put(None)
            w.join(timeout=5.0)

    # ----------------------------------------------------------------- write
    def _tmp_dir(self, step):
        # shared across ranks by construction: every rank assembles into the
        # SAME .tmp dir; the coordinator renames it once complete
        return os.path.join(self.directory, _step_dirname(step) + _TMP_SUFFIX)

    def _write(self, job):
        step = job["step"]
        tmp = self._tmp_dir(step)
        final = os.path.join(self.directory, _step_dirname(step))
        t0 = self._now()
        self._check("ckpt.serialize")
        os.makedirs(tmp, exist_ok=True)
        data_path = os.path.join(tmp, self._data_name())
        if job["chunks"]:
            with open(data_path, "wb") as f:
                np.savez(f, **job["chunks"])
                fsync_file(f)
        files = {}
        if os.path.exists(data_path):
            files[self._data_name()] = {
                "bytes": os.path.getsize(data_path),
                "crc32": _crc_file(data_path)}
        sidecar = {"rank": self.rank, "keys": job["entries"], "files": files}
        sc_path = os.path.join(tmp, f"meta_r{self.rank}.json")
        with open(sc_path + ".w", "w") as f:
            json.dump(sidecar, f)
            fsync_file(f)
        os.replace(sc_path + ".w", sc_path)
        self._phase("serialize", self._now() - t0)

        t0 = self._now()
        self._check("ckpt.commit")
        if self.rank == 0:
            self._commit(step, tmp, final, job["meta"])
            self._phase("commit", self._now() - t0)
            with self._lock:
                self.commits += 1
            mon = self.monitor
            if mon is not None:
                mon.checkpoint_result(ok=True, step=step)
            self._retain()

    def _commit(self, step, tmp, final, meta, timeout=120.0):
        """Coordinator: wait for every rank's sidecar, collate the manifest,
        fsync, and atomically rename the directory into place. The manifest
        is the commit record — a directory without one is torn by definition
        and ignored at restore."""
        deadline = self._now() + timeout    # injectable (skewable) clock
        while True:
            sidecars = [n for n in os.listdir(tmp)
                        if n.startswith("meta_r") and n.endswith(".json")]
            if len(sidecars) >= self.world_size:
                break
            if self._now() > deadline:
                raise TimeoutError(
                    f"checkpoint step {step}: {len(sidecars)}/"
                    f"{self.world_size} rank sidecars within {timeout}s — "
                    "refusing to commit an incomplete checkpoint")
            time.sleep(0.05)
        keys, files = {}, {}
        for name in sorted(sidecars):
            with open(os.path.join(tmp, name)) as f:
                part = json.load(f)
            files.update(part.get("files", {}))
            for key, entry in part["keys"].items():
                if key not in keys:
                    keys[key] = entry
                elif entry.get("kind") == "tensor":
                    have = {tuple(c["offset"]) for c in keys[key]["chunks"]}
                    for c in entry["chunks"]:
                        if tuple(c["offset"]) not in have:
                            keys[key]["chunks"].append(c)
        manifest = {"version": 1, "step": int(step),
                    "world_size": self.world_size,
                    "wall_time": time.time(),   # informational ONLY —
                    # discovery orders by step number, never by clock
                    "meta": meta, "keys": keys, "files": files}
        mpath = os.path.join(tmp, _MANIFEST)
        with open(mpath + ".w", "w") as f:
            json.dump(manifest, f)
            fsync_file(f)
        os.replace(mpath + ".w", mpath)
        fsync_dir(tmp)
        if os.path.isdir(final):   # a re-save of the same step replaces it
            shutil.rmtree(final)
        os.replace(tmp, final)
        fsync_dir(self.directory)

    # ------------------------------------------------------------- retention
    def _retain(self):
        """keep-last-N + keep-every-M sweep, plus stale .tmp debris from
        previous incarnations (anything not the newest tmp)."""
        steps = []
        for name in os.listdir(self.directory):
            step = _parse_step(name)
            if step is not None:
                steps.append(step)
        steps.sort()
        keep = set(steps[-self.keep_last:] if self.keep_last else steps)
        if self.keep_every > 0:
            keep.update(s for s in steps if s % self.keep_every == 0)
        for s in steps:
            if s not in keep:
                shutil.rmtree(
                    os.path.join(self.directory, _step_dirname(s)),
                    ignore_errors=True)
        newest = steps[-1] if steps else None
        for name in os.listdir(self.directory):
            if not name.endswith(_TMP_SUFFIX):
                continue
            step = _parse_step(name[:-len(_TMP_SUFFIX)])
            # a torn tmp dir older than the newest committed step can never
            # complete (its writer is gone) — debris
            if step is not None and newest is not None and step <= newest:
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    # --------------------------------------------------------------- restore
    def steps(self):
        """Committed (manifest-bearing) step numbers, ascending."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            step = _parse_step(name)
            if step is not None and os.path.exists(
                    os.path.join(self.directory, name, _MANIFEST)):
                out.append(step)
        return sorted(out)

    def latest_step(self):
        steps = self.steps()
        return steps[-1] if steps else None

    def _validate(self, d):
        """Load + integrity-check a checkpoint dir's manifest; raises
        _CorruptCheckpoint with the reason on any failure."""
        mpath = os.path.join(d, _MANIFEST)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise _CorruptCheckpoint(f"unreadable manifest: {e!r}")
        for fname, info in manifest.get("files", {}).items():
            fpath = os.path.join(d, fname)
            if not os.path.exists(fpath):
                raise _CorruptCheckpoint(f"missing shard file {fname}")
            size = os.path.getsize(fpath)
            if size != info.get("bytes"):
                raise _CorruptCheckpoint(
                    f"shard {fname}: {size} bytes, manifest says "
                    f"{info.get('bytes')} (truncated write?)")
            if _crc_file(fpath) != info.get("crc32"):
                raise _CorruptCheckpoint(f"shard {fname}: crc32 mismatch")
        return manifest

    def restore(self, provider, step=None):
        """Discover the newest complete checkpoint (or exactly `step`),
        rebuild provider state on the current mesh, and return the restored
        step number — or None when no intact checkpoint exists. Corrupt or
        torn directories are skipped with a CheckpointCorruptWarning."""
        t0 = self._now()
        candidates = self.steps()
        if step is not None:
            candidates = [s for s in candidates if s == int(step)]
        for s in sorted(candidates, reverse=True):
            d = os.path.join(self.directory, _step_dirname(s))
            try:
                manifest = self._validate(d)
            except _CorruptCheckpoint as e:
                warnings.warn(
                    f"checkpoint {d} failed validation ({e}); falling back "
                    f"to the previous manifest", CheckpointCorruptWarning)
                continue
            state = self._read_state(d, manifest, provider)
            provider.import_state(state)
            with self._lock:
                self.last_restored = {"step": s, "dir": d,
                                      "meta": manifest.get("meta", {})}
            dt = self._now() - t0
            self._phase("restore", dt)
            return s
        return None

    def _read_state(self, d, manifest, provider):
        """Manifest entries -> the provider's nested state shape, each array
        stitched from chunks against the CURRENT sharding of the provider's
        live value (mesh-aware: a different process count than at save time
        just reads different slices off the shared filesystem)."""
        from ..distributed.checkpoint import ChunkReader

        keys = manifest["keys"]
        # walk the provider's CURRENT state shape (not the flat key strings:
        # parameter names legitimately contain dots) so every target leaf is
        # matched to its manifest entry and its live value's sharding
        template = {k: v for k, v in provider.export_state().items()
                    if k != "meta"}
        reader = ChunkReader(d)

        def fill(node, prefix):
            out = {}
            for k, v in node.items():
                name = f"{prefix}{k}"
                if isinstance(v, dict):
                    out[k] = fill(v, f"{name}.")
                    continue
                entry = keys.get(name)
                if entry is None:
                    raise ValueError(
                        f"checkpoint {d} has no entry for {name!r} — "
                        "restoring into a different model/optimizer?")
                if entry["kind"] == "scalar":
                    out[k] = entry["value"]
                else:
                    out[k] = self._read_entry(reader, entry, v)
            return out

        try:
            state = fill(template, "")
        finally:
            reader.close()
        state["meta"] = dict(manifest.get("meta") or {})
        return state

    @staticmethod
    def _read_entry(reader, entry, like):
        import jax

        shape = tuple(entry["shape"])
        full = tuple(slice(None) for _ in shape)
        if isinstance(like, jax.Array) and not isinstance(
                like, jax.core.Tracer) and tuple(like.shape) == shape:
            sharding = like.sharding
            try:
                return jax.make_array_from_callback(
                    shape, sharding,
                    lambda idx, e=entry: reader.read(e, idx))
            except Exception:   # exotic sharding: fall through to full read
                pass
        return reader.read(entry, full)
