"""paddle.save / paddle.load. Reference: python/paddle/framework/io.py (pickle-based).

Arrays are stored as numpy inside the pickle (like the reference); Tensors round-trip.

Crash safety (round 10): ``save`` writes to a temp file in the target
directory, fsyncs, then ``os.replace``s — a preemption mid-save can never
leave a truncated file where a good checkpoint was. Files written by THIS
framework carry a format marker so ``load`` never has to guess whether a
dict of ndarrays is ours (round-trip unchanged) or a real PaddlePaddle
``.pdparams`` (convert to Tensors); the heuristic remains only for
marker-less files from either world.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..tensor import Tensor

# top-level wrapper key identifying a file written by THIS save(). Loading a
# marked file always routes through _unpack — no reference-format heuristics.
_FORMAT_KEY = "__paddle_tpu_save_format__"
_FORMAT_VERSION = 1


class _TensorPayload:
    def __init__(self, array, stop_gradient):
        self.array = array
        self.stop_gradient = stop_gradient


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value), obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_pack(v) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_pack(v) for v in obj)
    return obj


def _unpack(obj):
    import jax.numpy as jnp

    if isinstance(obj, _TensorPayload):
        return Tensor(jnp.asarray(obj.array), stop_gradient=obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_unpack(v) for v in obj)
    return obj


def fsync_file(f):
    """flush + fsync a file object; best-effort on filesystems without it."""
    f.flush()
    try:
        os.fsync(f.fileno())
    except OSError:  # pragma: no cover - exotic fs
        pass


def fsync_dir(path):
    """fsync a DIRECTORY so a rename into it survives power loss (POSIX:
    replace() orders the entry, the dir fsync makes it durable)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover - e.g. non-POSIX
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    # temp file IN the target directory: os.replace must not cross devices,
    # and a same-dir rename is atomic on POSIX
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            pickle.dump({_FORMAT_KEY: _FORMAT_VERSION, "obj": _pack(obj)},
                        f, protocol=protocol)
            fsync_file(f)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    fsync_dir(d or ".")


def _from_reference_format(obj):
    """Convert values from a REAL PaddlePaddle checkpoint (.pdparams /
    .pdopt) into Tensors.

    Reference io.py:413 (_pickle_save) reduces eager Tensors to
    `(tuple, ((name, ndarray),))` and DenseTensors to an `eval` returning the
    bare ndarray — both unpickle fine without paddle installed, arriving here
    as `(name, ndarray)` tuples / plain ndarrays. This is the IR-adaptor role
    for checkpoints (VERDICT r3 missing #7): any pretrained Paddle state dict
    loads directly."""
    if (isinstance(obj, tuple) and len(obj) == 2
            and isinstance(obj[0], str) and isinstance(obj[1], np.ndarray)):
        import jax.numpy as jnp

        return Tensor(jnp.asarray(obj[1]))
    if isinstance(obj, np.ndarray) and obj.dtype != object:
        import jax.numpy as jnp

        return Tensor(jnp.asarray(obj))
    if isinstance(obj, dict):
        return {k: _from_reference_format(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_from_reference_format(v) for v in obj]
    return obj


def _looks_like_reference_ckpt(obj):
    """True when EVERY value has a reference reduce shape and none is our own
    _TensorPayload. Only consulted for files WITHOUT the format marker: our
    own saves are self-identifying, so an all-ndarray dict here is a real
    reference DenseTensor state dict and converts to Tensors (pre-marker the
    all-ndarray case was ambiguous with our own save format and had to
    round-trip unchanged — the round-10 marker removed that ambiguity)."""
    if not isinstance(obj, dict):
        return False
    vals = list(obj.values())
    if not vals or any(isinstance(v, _TensorPayload) for v in vals):
        return False

    def _is_eager_tuple(v):
        return (isinstance(v, tuple) and len(v) == 2
                and isinstance(v[0], str) and isinstance(v[1], np.ndarray))

    return all(_is_eager_tuple(v)
               or (isinstance(v, np.ndarray) and v.dtype != object)
               for v in vals)


def load(path, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    if isinstance(obj, dict) and obj.get(_FORMAT_KEY) is not None:
        return _unpack(obj["obj"])
    if _looks_like_reference_ckpt(obj):
        return _from_reference_format(obj)
    return _unpack(obj)
