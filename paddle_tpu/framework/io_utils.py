"""paddle.save / paddle.load. Reference: python/paddle/framework/io.py (pickle-based).

Arrays are stored as numpy inside the pickle (like the reference); Tensors round-trip.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..tensor import Tensor


class _TensorPayload:
    def __init__(self, array, stop_gradient):
        self.array = array
        self.stop_gradient = stop_gradient


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value), obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_pack(v) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_pack(v) for v in obj)
    return obj


def _unpack(obj):
    import jax.numpy as jnp

    if isinstance(obj, _TensorPayload):
        return Tensor(jnp.asarray(obj.array), stop_gradient=obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_unpack(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def _from_reference_format(obj):
    """Convert values from a REAL PaddlePaddle checkpoint (.pdparams /
    .pdopt) into Tensors.

    Reference io.py:413 (_pickle_save) reduces eager Tensors to
    `(tuple, ((name, ndarray),))` and DenseTensors to an `eval` returning the
    bare ndarray — both unpickle fine without paddle installed, arriving here
    as `(name, ndarray)` tuples / plain ndarrays. This is the IR-adaptor role
    for checkpoints (VERDICT r3 missing #7): any pretrained Paddle state dict
    loads directly."""
    if (isinstance(obj, tuple) and len(obj) == 2
            and isinstance(obj[0], str) and isinstance(obj[1], np.ndarray)):
        import jax.numpy as jnp

        return Tensor(jnp.asarray(obj[1]))
    if isinstance(obj, np.ndarray) and obj.dtype != object:
        import jax.numpy as jnp

        return Tensor(jnp.asarray(obj))
    if isinstance(obj, dict):
        return {k: _from_reference_format(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_from_reference_format(v) for v in obj]
    return obj


def _looks_like_reference_ckpt(obj):
    """True only when EVERY value has the reference reduce shape and none is
    our own _TensorPayload (a mixed dict saved by this framework must route
    through _unpack, or its payload wrappers would leak to the caller)."""
    if not isinstance(obj, dict):
        return False
    vals = list(obj.values())
    if not vals or any(isinstance(v, _TensorPayload) for v in vals):
        return False

    def _is_eager_tuple(v):
        return (isinstance(v, tuple) and len(v) == 2
                and isinstance(v[0], str) and isinstance(v[1], np.ndarray))

    # require at least one eager-tensor tuple (every real dygraph state dict
    # has them) — an all-ndarray dict is ambiguous with OUR OWN save format
    # and must round-trip unchanged
    if not any(_is_eager_tuple(v) for v in vals):
        return False
    return all(_is_eager_tuple(v) or isinstance(v, np.ndarray) for v in vals)


def load(path, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    if _looks_like_reference_ckpt(obj):
        return _from_reference_format(obj)
    return _unpack(obj)
