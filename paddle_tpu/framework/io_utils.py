"""paddle.save / paddle.load. Reference: python/paddle/framework/io.py (pickle-based).

Arrays are stored as numpy inside the pickle (like the reference); Tensors round-trip.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..tensor import Tensor


class _TensorPayload:
    def __init__(self, array, stop_gradient):
        self.array = array
        self.stop_gradient = stop_gradient


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value), obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_pack(v) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_pack(v) for v in obj)
    return obj


def _unpack(obj):
    import jax.numpy as jnp

    if isinstance(obj, _TensorPayload):
        return Tensor(jnp.asarray(obj.array), stop_gradient=obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_unpack(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return _unpack(pickle.load(f))
