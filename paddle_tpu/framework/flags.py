"""Global flag registry: paddle.set_flags / paddle.get_flags.

Reference: paddle/common/flags.cc (typed FLAGS_* definitions) +
python/paddle/base/framework.py set_flags/get_flags. TPU-native: flags that
map onto jax/XLA config apply immediately through a setter hook; the rest are
typed, validated state that subsystems read (e.g. FLAGS_check_nan_inf is
consulted by the op dispatcher). Env vars named FLAGS_* seed initial values.
"""
from __future__ import annotations

import os
from typing import Any, Callable


class _Flag:
    def __init__(self, name, default, typ, help_str="", on_set: Callable | None = None):
        self.name = name
        self.type = typ
        self.help = help_str
        self.on_set = on_set
        env = os.environ.get(name)
        self.value = self._coerce(env) if env is not None else default

    def _coerce(self, v):
        if self.type is bool:
            if isinstance(v, str):
                return v.lower() in ("1", "true", "yes", "on")
            return bool(v)
        return self.type(v)

    def set(self, v):
        self.value = self._coerce(v)
        if self.on_set is not None:
            self.on_set(self.value)


def _set_matmul_precision(val: str):
    import jax

    allowed = {"default", "high", "highest", "bfloat16", "tensorfloat32", "float32"}
    if val in allowed:
        jax.config.update("jax_default_matmul_precision",
                          None if val == "default" else val)


def _set_deterministic(val: bool):
    # XLA determinism: affects scatter/reduction order on device. XLA_FLAGS is
    # read once at client creation — setting this after the backend exists
    # cannot change the running process, so say so instead of silently no-oping.
    flags = os.environ.get("XLA_FLAGS", "")
    tok = "--xla_gpu_deterministic_ops=true"
    if val and tok not in flags:
        os.environ["XLA_FLAGS"] = (flags + " " + tok).strip()
    elif not val and tok in flags:
        os.environ["XLA_FLAGS"] = flags.replace(tok, "").strip()
    import jax._src.xla_bridge as _xb

    if getattr(_xb, "_backends", None):
        import warnings

        warnings.warn(
            "FLAGS_cudnn_deterministic changes XLA_FLAGS, which the already-"
            "initialized XLA backend will not re-read; set it before the first "
            "device op (or in the environment) for it to take effect",
            RuntimeWarning)


_REGISTRY: dict[str, _Flag] = {}


def _define(name, default, typ, help_str="", on_set=None):
    _REGISTRY[name] = _Flag(name, default, typ, help_str, on_set)


# ------------------------------------------------------------------ definitions
# numerics / debugging
_define("FLAGS_check_nan_inf", False, bool,
        "scan op outputs for NaN/Inf at eager dispatch (debugging)")
_define("FLAGS_check_nan_inf_level", 0, int,
        "0: error on NaN/Inf; 1+: warn only")
_define("FLAGS_cudnn_deterministic", False, bool,
        "deterministic device kernels", _set_deterministic)
_define("FLAGS_matmul_precision", "default", str,
        "default|high|highest — MXU accumulation precision",
        _set_matmul_precision)
# memory (informational on TPU: XLA owns allocation; kept for API parity)
_define("FLAGS_fraction_of_gpu_memory_to_use", 0.92, float,
        "device memory fraction (PJRT preallocation)")
_define("FLAGS_allocator_strategy", "auto_growth", str,
        "allocator strategy (XLA-managed on TPU)")
_define("FLAGS_eager_delete_tensor_gb", 0.0, float, "GC threshold")
# execution
_define("FLAGS_use_mkldnn", False, bool, "no-op on TPU")
_define("FLAGS_benchmark", False, bool, "sync-and-time every op")
_define("FLAGS_paddle_num_threads", 1, int, "host threads per op")
# distributed
_define("FLAGS_call_stack_level", 1, int, "error verbosity")
_define("FLAGS_log_memory_stats", False, bool, "log live/peak memory each step")


def set_flags(flags: dict[str, Any]):
    """Reference: framework.py set_flags. Unknown names raise ValueError."""
    if not isinstance(flags, dict):
        raise TypeError("set_flags expects a dict of {flag_name: value}")
    for k, v in flags.items():
        if k not in _REGISTRY:
            raise ValueError(f"flag {k!r} is not defined (see paddle.get_flags())")
        _REGISTRY[k].set(v)


def get_flags(flags=None) -> dict[str, Any]:
    """Reference: framework.py get_flags. None → all flags."""
    if flags is None:
        return {k: f.value for k, f in _REGISTRY.items()}
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        if k not in _REGISTRY:
            raise ValueError(f"flag {k!r} is not defined")
        out[k] = _REGISTRY[k].value
    return out


def flag(name: str):
    """Fast internal accessor (no dict copy)."""
    return _REGISTRY[name].value
