"""paddle.signal — STFT / iSTFT.

Reference: python/paddle/signal.py (stft:153, istft:305). Framing is a strided
gather + batched rfft/fft (XLA FFT); istft does the standard overlap-add with
window-envelope normalization.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .audio.functional import get_window
from .ops import apply_op
from .tensor import Tensor


def _prep_window(window, win_length, n_fft, dtype=jnp.float32):
    if window is None:
        w = jnp.ones((win_length,), dtype)
    elif isinstance(window, Tensor):
        w = window._value.astype(dtype)
    elif isinstance(window, str) or isinstance(window, (tuple, list)):
        w = get_window(window, win_length)._value.astype(dtype)
    else:
        w = jnp.asarray(window, dtype)
    if win_length < n_fft:
        lp = (n_fft - win_length) // 2
        w = jnp.pad(w, (lp, n_fft - win_length - lp))
    return w


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """x: [..., T] → complex [..., n_fft//2+1 (or n_fft), n_frames]."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    w = _prep_window(window, win_length, n_fft)

    def f(v):
        v = v.astype(jnp.float32)
        if center:
            pad = n_fft // 2
            cfg = [(0, 0)] * (v.ndim - 1) + [(pad, pad)]
            v = jnp.pad(v, cfg, mode=pad_mode)
        t = v.shape[-1]
        n_frames = 1 + (t - n_fft) // hop_length
        idx = (jnp.arange(n_frames)[:, None] * hop_length
               + jnp.arange(n_fft)[None, :])
        frames = v[..., idx] * w
        spec = jnp.fft.rfft(frames, axis=-1) if onesided else \
            jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.float32(n_fft))
        return jnp.swapaxes(spec, -1, -2)  # [..., freq, frames]

    return apply_op(f, "stft", x)


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT via overlap-add; x: [..., freq, n_frames]."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    w = _prep_window(window, win_length, n_fft)

    def f(v):
        spec = jnp.swapaxes(v, -1, -2)  # [..., frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.float32(n_fft))
        frames = (jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided
                  else jnp.fft.ifft(spec, axis=-1).real)
        frames = frames * w
        n_frames = frames.shape[-2]
        out_len = n_fft + hop_length * (n_frames - 1)
        lead = frames.shape[:-2]
        flat = frames.reshape((-1, n_frames, n_fft))
        idx = (jnp.arange(n_frames)[:, None] * hop_length
               + jnp.arange(n_fft)[None, :]).reshape(-1)

        def ola(fr):
            sig = jnp.zeros((out_len,), fr.dtype).at[idx].add(fr.reshape(-1))
            return sig

        sig = jax.vmap(ola)(flat).reshape(lead + (out_len,))
        env = jnp.zeros((out_len,), w.dtype).at[idx].add(
            jnp.tile(w * w, (n_frames,)))
        sig = sig / jnp.maximum(env, 1e-11)
        if center:
            sig = sig[..., n_fft // 2: out_len - n_fft // 2]
        if length is not None:
            sig = sig[..., :length]
        return sig

    return apply_op(f, "istft", x)
