"""paddle.fft — spectral ops over XLA's FFT.

Reference: python/paddle/fft.py (fft/ifft/rfft/... with norm= semantics).
TPU note: XLA lowers FFTs natively; stick to power-of-two sizes for the fast
path on device. All functions accept Tensor or array-like and return Tensor.
"""
from __future__ import annotations

import jax.numpy as jnp

from .ops import apply_op
from .tensor import Tensor


def _wrap1(jfn, name):
    def fn(x, n=None, axis=-1, norm="backward", **kw):
        return apply_op(lambda v: jfn(v, n=n, axis=axis, norm=norm), name, x)

    fn.__name__ = name
    return fn


def _wrapn(jfn, name, default_axes=None):
    def fn(x, s=None, axes=default_axes, norm="backward", **kw):
        return apply_op(lambda v: jfn(v, s=s, axes=axes, norm=norm), name, x)

    fn.__name__ = name
    return fn


fft = _wrap1(jnp.fft.fft, "fft")
ifft = _wrap1(jnp.fft.ifft, "ifft")
rfft = _wrap1(jnp.fft.rfft, "rfft")
irfft = _wrap1(jnp.fft.irfft, "irfft")
hfft = _wrap1(jnp.fft.hfft, "hfft")
ihfft = _wrap1(jnp.fft.ihfft, "ihfft")

fft2 = _wrapn(jnp.fft.fft2, "fft2", default_axes=(-2, -1))
ifft2 = _wrapn(jnp.fft.ifft2, "ifft2", default_axes=(-2, -1))
rfft2 = _wrapn(jnp.fft.rfft2, "rfft2", default_axes=(-2, -1))
irfft2 = _wrapn(jnp.fft.irfft2, "irfft2", default_axes=(-2, -1))

fftn = _wrapn(jnp.fft.fftn, "fftn")
ifftn = _wrapn(jnp.fft.ifftn, "ifftn")
rfftn = _wrapn(jnp.fft.rfftn, "rfftn")
irfftn = _wrapn(jnp.fft.irfftn, "irfftn")


def fftfreq(n, d=1.0, dtype=None):
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None):
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or "float32"))


def fftshift(x, axes=None):
    return apply_op(lambda v: jnp.fft.fftshift(v, axes=axes), "fftshift", x)


def ifftshift(x, axes=None):
    return apply_op(lambda v: jnp.fft.ifftshift(v, axes=axes), "ifftshift", x)
