"""paddle.fft — spectral ops over XLA's FFT.

Reference: python/paddle/fft.py (fft/ifft/rfft/... with norm= semantics).
TPU note: XLA lowers FFTs natively; stick to power-of-two sizes for the fast
path on device. All functions accept Tensor or array-like and return Tensor.
"""
from __future__ import annotations

import jax.numpy as jnp

from .ops import apply_op
from .tensor import Tensor


def _wrap1(jfn, name):
    def fn(x, n=None, axis=-1, norm="backward", **kw):
        return apply_op(lambda v: jfn(v, n=n, axis=axis, norm=norm), name, x)

    fn.__name__ = name
    return fn


def _wrapn(jfn, name, default_axes=None):
    def fn(x, s=None, axes=default_axes, norm="backward", **kw):
        return apply_op(lambda v: jfn(v, s=s, axes=axes, norm=norm), name, x)

    fn.__name__ = name
    return fn


fft = _wrap1(jnp.fft.fft, "fft")
ifft = _wrap1(jnp.fft.ifft, "ifft")
rfft = _wrap1(jnp.fft.rfft, "rfft")
irfft = _wrap1(jnp.fft.irfft, "irfft")
hfft = _wrap1(jnp.fft.hfft, "hfft")
ihfft = _wrap1(jnp.fft.ihfft, "ihfft")

fft2 = _wrapn(jnp.fft.fft2, "fft2", default_axes=(-2, -1))
ifft2 = _wrapn(jnp.fft.ifft2, "ifft2", default_axes=(-2, -1))
rfft2 = _wrapn(jnp.fft.rfft2, "rfft2", default_axes=(-2, -1))
irfft2 = _wrapn(jnp.fft.irfft2, "irfft2", default_axes=(-2, -1))

fftn = _wrapn(jnp.fft.fftn, "fftn")
ifftn = _wrapn(jnp.fft.ifftn, "ifftn")
rfftn = _wrapn(jnp.fft.rfftn, "rfftn")
irfftn = _wrapn(jnp.fft.irfftn, "irfftn")


def fftfreq(n, d=1.0, dtype=None):
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None):
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or "float32"))


def fftshift(x, axes=None):
    return apply_op(lambda v: jnp.fft.fftshift(v, axes=axes), "fftshift", x)


def ifftshift(x, axes=None):
    return apply_op(lambda v: jnp.fft.ifftshift(v, axes=axes), "ifftshift", x)


def _hermitian_nd(fn, name, n_axes):
    """Reference: python/paddle/fft.py hfft2/hfftn/ihfft2/ihfftn."""

    def wrapped(x, s=None, axes=None, norm="backward", name_arg=None):
        def f(v):
            ax = tuple(axes) if axes is not None else tuple(
                range(-(n_axes or v.ndim), 0))
            return fn(v, s=s, axes=ax, norm=norm)

        return apply_op(f, name, x)

    return wrapped


def _hfftn_impl(v, s=None, axes=None, norm="backward"):
    # scipy identity: hfftn(x, s) == irfftn(conj(x), s) * prod(s) under the
    # backward norm (hfft(a, n) == irfft(conj(a), n) * n generalized per axis)
    axes = tuple(axes)
    if s is None:
        shape = [2 * (v.shape[a] - 1) if a == axes[-1] or a == v.ndim + axes[-1]
                 else v.shape[a] for a in axes]
    else:
        shape = list(s)
    out = jnp.fft.irfftn(jnp.conj(v), s=shape, axes=axes, norm=norm)
    if norm in (None, "backward"):
        n = 1
        for d in shape:
            n *= d
        out = out * n
    elif norm == "ortho":
        n = 1
        for d in shape:
            n *= d
        out = out * jnp.sqrt(n)
    return out


def _ihfftn_impl(v, s=None, axes=None, norm="backward"):
    # scipy identity: ihfftn(x, s) == conj(rfftn(x, s)) / prod(s) (backward)
    axes = tuple(axes)
    shape = list(s) if s is not None else [v.shape[a] for a in axes]
    out = jnp.conj(jnp.fft.rfftn(v.astype(jnp.float64)
                                 if v.dtype.kind != "c" else v,
                                 s=shape, axes=axes, norm=norm))
    if norm in (None, "backward"):
        n = 1
        for d in shape:
            n *= d
        out = out / n
    elif norm == "ortho":
        n = 1
        for d in shape:
            n *= d
        out = out / jnp.sqrt(n)
    return out


hfft2 = _hermitian_nd(_hfftn_impl, "hfft2", 2)
hfftn = _hermitian_nd(_hfftn_impl, "hfftn", None)
ihfft2 = _hermitian_nd(_ihfftn_impl, "ihfft2", 2)
ihfftn = _hermitian_nd(_ihfftn_impl, "ihfftn", None)
