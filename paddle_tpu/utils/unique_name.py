"""Unique-name generator with scoped guards.

Reference: python/paddle/utils/unique_name.py (generate/guard/switch over a
UniqueNameGenerator). Names here back Tensor.name / optimizer accumulator keys,
so `guard()` gives reproducible names when re-instantiating a model in one
process (e.g. checkpoint resume tests, program re-tracing).
"""
from __future__ import annotations

import contextlib

from ..tensor import Tensor


class NameGenerator:
    def __init__(self):
        self.ids: dict[str, int] = {}

    def generate(self, key: str = "tmp") -> str:
        n = self.ids.get(key, 0)
        self.ids[key] = n + 1
        return f"{key}_{n}"


_generator = NameGenerator()


def generate(key: str = "tmp") -> str:
    return _generator.generate(key)


def switch(new_generator=None):
    """Swap the active generator AND the Tensor id counter; returns the old pair."""
    global _generator
    old = (_generator, Tensor._iid)
    _generator = new_generator if new_generator is not None else NameGenerator()
    Tensor._iid = 0
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Fresh (or given) name scope inside the `with`; restores the outer scope —
    including the Tensor auto-name counter — on exit."""
    old_gen, old_iid = switch(new_generator)
    try:
        yield
    finally:
        global _generator
        _generator = old_gen
        Tensor._iid = old_iid
