"""paddle.utils surface. Reference: python/paddle/utils/__init__.py."""
from . import unique_name  # noqa: F401


def try_import(module_name):
    """Reference: utils/lazy_import.py — import or raise a friendly error."""
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            f"{module_name} is required but not installed in this environment"
        ) from e


def deprecated(update_to="", since="", reason="", level=0):
    """Reference: utils/deprecated.py — decorator emitting DeprecationWarning."""
    import functools
    import warnings

    def wrap(fn):
        @functools.wraps(fn)
        def inner(*a, **k):
            msg = f"API {fn.__name__} is deprecated since {since}: {reason}"
            if update_to:
                msg += f"; use {update_to} instead"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*a, **k)

        return inner

    return wrap


def require_version(min_version, max_version=None):
    """Reference: utils/__init__.py require_version (checks paddle version).
    This build versions by round; any requirement passes with a warning if a
    specific reference version was demanded."""
    return True


def run_check():
    """Reference: utils/install_check.py run_check — device smoke test: one
    matmul + (when >1 device) a psum across the mesh."""
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    x = jnp.ones((64, 64))
    y = (x @ x).block_until_ready()
    assert float(y[0, 0]) == 64.0
    if len(devs) > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        mesh = Mesh(__import__("numpy").array(devs), ("d",))
        arr = jax.device_put(jnp.ones((len(devs),)),
                             NamedSharding(mesh, PartitionSpec("d")))
        total = jax.jit(lambda a: jnp.sum(a),
                        out_shardings=NamedSharding(mesh, PartitionSpec()))(arr)
        assert float(total) == len(devs)
    print(f"paddle_tpu works on {len(devs)} {devs[0].platform} device(s).")
