"""paddle.utils surface. Reference: python/paddle/utils/__init__.py."""
from . import unique_name  # noqa: F401


def try_import(module_name):
    """Reference: utils/lazy_import.py — import or raise a friendly error."""
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            f"{module_name} is required but not installed in this environment"
        ) from e
