"""Custom operator system.

Reference: python/paddle/utils/cpp_extension/ (load() JIT-compiles C++ sources
into an importable op library) and the custom-op registration machinery
(paddle/fluid/framework/custom_operator.cc).

TPU-native split:

- **Device custom ops** are Pallas/jax functions — ``register_op`` puts them
  behind the same ``apply_op`` dispatch as every built-in (autograd via
  jax.vjp, optional custom vjp, works under jit/GSPMD). This is the path that
  runs on the MXU.
- **Host custom ops** are real native code: ``load()`` compiles C++ sources
  with g++ into a shared library and exposes ``extern "C"`` functions through
  ctypes. They run on host buffers (the reference's CPU-kernel custom ops);
  useful for data-loader transforms and CPU pre/post-processing, and they
  compose with the op layer through ``lib.elementwise`` wrappers.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

import numpy as np

from ..ops import apply_op
from ..tensor import Tensor

_BUILD_ROOT = os.path.join(tempfile.gettempdir(), "paddle_tpu_extensions")


# ------------------------------------------------------------------ device ops
_CUSTOM_OPS: dict = {}


def register_op(name, fn, vjp=None):
    """Register a jax/Pallas function as a paddle op.

    fn(*jax_arrays, **static_kwargs) -> jax array(s). Optional
    vjp(primals, cotangents) -> input cotangents installs a custom gradient
    (jax.custom_vjp); otherwise jax differentiates fn directly.
    Returns the dispatchable callable (also available via ``get_op(name)``).
    """
    import jax

    if vjp is not None:
        wrapped = jax.custom_vjp(fn)

        def fwd(*args, **kw):
            return fn(*args, **kw), args

        def bwd(primals, ct):
            return tuple(vjp(primals, ct))

        wrapped.defvjp(fwd, bwd)
        impl = wrapped
    else:
        impl = fn

    def dispatch(*tensors, **kwargs):
        return apply_op(impl, name, *tensors, **kwargs)

    dispatch.__name__ = name
    _CUSTOM_OPS[name] = dispatch
    return dispatch


def get_op(name):
    return _CUSTOM_OPS[name]


# ------------------------------------------------------------------ host ops
_C_DTYPES = {
    np.dtype("float32"): ctypes.c_float,
    np.dtype("float64"): ctypes.c_double,
    np.dtype("int32"): ctypes.c_int32,
    np.dtype("int64"): ctypes.c_int64,
}


class CustomOpLibrary:
    """A compiled extension: ctypes handle + paddle-level helpers."""

    def __init__(self, name, so_path):
        self.name = name
        self.so_path = so_path
        self._lib = ctypes.CDLL(so_path)

    def __getattr__(self, fn_name):
        return getattr(self._lib, fn_name)

    def elementwise(self, fn_name, x, out_dtype=None):
        """Run ``void fn(const T* in, T* out, int64_t n)`` over a tensor's host
        copy; returns a new Tensor. The convention covers map-style host ops."""
        arr = np.ascontiguousarray(
            np.asarray(x._value if isinstance(x, Tensor) else x))
        ctype = _C_DTYPES.get(arr.dtype)
        if ctype is None:
            raise TypeError(f"unsupported dtype {arr.dtype} for host custom op")
        out = np.empty_like(arr, dtype=out_dtype or arr.dtype)
        fn = getattr(self._lib, fn_name)
        fn.argtypes = [ctypes.POINTER(ctype),
                       ctypes.POINTER(_C_DTYPES[out.dtype]),
                       ctypes.c_int64]
        fn.restype = None
        fn(arr.ctypes.data_as(ctypes.POINTER(ctype)),
           out.ctypes.data_as(ctypes.POINTER(_C_DTYPES[out.dtype])),
           ctypes.c_int64(arr.size))
        import jax.numpy as jnp

        return Tensor(jnp.asarray(out))


def load(name, sources, extra_cxx_flags=(), extra_ldflags=(), build_directory=None,
         verbose=False):
    """JIT-compile C++ `sources` into a shared library (reference
    cpp_extension.load). Caches on (sources content, flags)."""
    build_dir = build_directory or _BUILD_ROOT
    os.makedirs(build_dir, exist_ok=True)
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join([*extra_cxx_flags, *extra_ldflags]).encode())
    so_path = os.path.join(build_dir, f"{name}_{h.hexdigest()[:16]}.so")
    if not os.path.exists(so_path):
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
               *extra_cxx_flags, *sources, "-o", so_path, *extra_ldflags]
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        r = subprocess.run(cmd, capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(f"compilation of {name} failed:\n{r.stderr}")
    return CustomOpLibrary(name, so_path)


class CppExtension:
    """setup()-style spec (reference cpp_extension.CppExtension) — thin data
    holder; `load` is the JIT path used in this build."""

    def __init__(self, sources, *args, **kwargs):
        self.sources = sources
        self.kwargs = kwargs
