"""Scalar/metric logging (VisualDL LogWriter role).

Reference: the training stack logs through visualdl.LogWriter
(add_scalar/add_histogram) — an external package. This build ships a
dependency-free writer with the same surface: JSONL records under a run
directory, append-only and crash-safe, plus a reader for analysis/plotting.
"""
from __future__ import annotations

import json
import os
import time


class LogWriter:
    def __init__(self, logdir="./log", file_name="", flush_secs=5, **kwargs):
        os.makedirs(logdir, exist_ok=True)
        self.logdir = logdir
        name = file_name or f"vdlrecords.{int(time.time())}.log"
        self.path = os.path.join(logdir, name)
        # block-buffered so flush_secs actually batches writes; flush() and
        # close() make records durable
        self._f = open(self.path, "a")
        self._flush_secs = flush_secs
        self._last_flush = time.monotonic()

    # ------------------------------------------------------------------ records
    def _write(self, record, walltime=None):
        record["wall_time"] = time.time() if walltime is None else walltime
        self._f.write(json.dumps(record) + "\n")
        if time.monotonic() - self._last_flush > self._flush_secs:
            self.flush()

    def add_scalar(self, tag, value, step=None, walltime=None):
        self._write({"type": "scalar", "tag": tag, "value": float(value),
                     "step": step}, walltime=walltime)

    def add_scalars(self, main_tag, tag_value_dict, step=None):
        for k, v in tag_value_dict.items():
            self.add_scalar(f"{main_tag}/{k}", v, step)

    def add_histogram(self, tag, values, step=None, buckets=10):
        import numpy as np

        arr = np.asarray(values, dtype="float64").reshape(-1)
        counts, edges = np.histogram(arr, bins=buckets)
        self._write({"type": "histogram", "tag": tag, "step": step,
                     "counts": counts.tolist(), "edges": edges.tolist(),
                     "min": float(arr.min()), "max": float(arr.max()),
                     "mean": float(arr.mean())})

    def add_text(self, tag, text, step=None):
        self._write({"type": "text", "tag": tag, "text": str(text), "step": step})

    def add_hparams(self, hparams_dict, metrics_list=(), **kwargs):
        self._write({"type": "hparams", "hparams": dict(hparams_dict),
                     "metrics": list(metrics_list)})

    # ------------------------------------------------------------------ lifecycle
    def flush(self):
        self._f.flush()
        self._last_flush = time.monotonic()

    def close(self):
        if self._f:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def read_log(path):
    """Load a LogWriter file back as a list of record dicts."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def scalars(path, tag=None):
    """(step, value) series for `tag` (or {tag: series} for all scalars)."""
    recs = [r for r in read_log(path) if r["type"] == "scalar"]
    if tag is not None:
        return [(r["step"], r["value"]) for r in recs if r["tag"] == tag]
    series: dict = {}
    for r in recs:
        series.setdefault(r["tag"], []).append((r["step"], r["value"]))
    return series
