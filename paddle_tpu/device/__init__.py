"""paddle.device namespace. Reference: python/paddle/device/."""
from __future__ import annotations

import jax

from ..framework.device import (  # noqa: F401
    CPUPlace, CUDAPlace, Place, TPUPlace, XPUPlace, device_count, get_device, get_place,
    is_compiled_with_cuda, is_compiled_with_tpu, is_compiled_with_xpu, set_device,
)

__all__ = ["set_device", "get_device", "get_all_device_type", "get_all_custom_device_type",
           "get_available_device", "get_available_custom_device", "device_count",
           "synchronize", "cuda", "Stream", "Event", "stream_guard", "current_stream"]


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return [p for p in get_all_device_type() if p not in ("cpu", "gpu")]


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices() if d.platform not in ("cpu", "gpu")]


def synchronize(device=None):
    """Block until all queued device work completes (XLA is async by default)."""
    jax.effects_barrier() if hasattr(jax, "effects_barrier") else None
    import jax.numpy as jnp

    jnp.zeros(()).block_until_ready()


class Stream:
    """XLA schedules its own streams; this exists for API parity and ordering is a no-op
    (all work on one device is program-ordered)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def query(self):
        return True


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_current_stream = Stream()


def current_stream(device=None):
    return _current_stream


import contextlib


@contextlib.contextmanager
def stream_guard(stream):
    yield


class cuda:
    """paddle.device.cuda compat shim — maps to the accelerator device."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def current_stream(device=None):
        return _current_stream

    @staticmethod
    def max_memory_allocated(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("peak_bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def memory_allocated(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def max_memory_reserved(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("peak_bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def memory_reserved(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("bytes_limit", 0)
        except Exception:
            return 0

    @staticmethod
    def empty_cache():
        pass
