"""paddle.device namespace. Reference: python/paddle/device/."""
from __future__ import annotations

import jax

from ..framework.device import (  # noqa: F401
    CPUPlace, CUDAPlace, Place, TPUPlace, XPUPlace, device_count, get_device, get_place,
    is_compiled_with_cuda, is_compiled_with_tpu, is_compiled_with_xpu, set_device,
)

__all__ = ["set_device", "get_device", "get_all_device_type", "get_all_custom_device_type",
           "get_available_device", "get_available_custom_device", "device_count",
           "synchronize", "cuda", "Stream", "Event", "stream_guard", "current_stream",
           "memory_stats", "memory_allocated", "max_memory_allocated",
           "memory_reserved", "max_memory_reserved", "empty_cache"]


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return [p for p in get_all_device_type() if p not in ("cpu", "gpu")]


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices() if d.platform not in ("cpu", "gpu")]


def synchronize(device=None):
    """Block until all queued device work completes (XLA is async by default)."""
    jax.effects_barrier() if hasattr(jax, "effects_barrier") else None
    import jax.numpy as jnp

    jnp.zeros(()).block_until_ready()


# ------------------------------------------------------------------ memory
def _resolve_device(device=None):
    if device is None:
        return jax.devices()[0]
    if isinstance(device, int):
        return jax.devices()[device]
    if hasattr(device, "memory_stats"):
        return device
    plat, _, idx = str(device).partition(":")
    devs = jax.devices(plat) if plat else jax.devices()
    return devs[int(idx) if idx else 0]


def memory_stats(device=None):
    """Raw PJRT allocator stats (reference: phi memory stats / paddle.device.cuda
    memory API family). TPU returns bytes_in_use / peak_bytes_in_use /
    bytes_limit etc.; backends without an instrumented allocator return {}."""
    d = _resolve_device(device)
    return d.memory_stats() or {}


def _live_bytes(d):
    # fallback accounting: sum of live jax arrays resident on this device
    total = 0
    for arr in jax.live_arrays():
        try:
            for sh in arr.addressable_shards:
                if sh.device == d:
                    total += sh.data.nbytes
        except Exception:
            continue
    return total


def memory_allocated(device=None):
    """Bytes currently allocated on the device (live buffers)."""
    d = _resolve_device(device)
    stats = d.memory_stats() or {}
    if "bytes_in_use" in stats:
        return int(stats["bytes_in_use"])
    return _live_bytes(d)


def max_memory_allocated(device=None):
    """Peak bytes allocated (PJRT peak counter; falls back to current)."""
    d = _resolve_device(device)
    stats = d.memory_stats() or {}
    if "peak_bytes_in_use" in stats:
        return int(stats["peak_bytes_in_use"])
    return _live_bytes(d)


def memory_reserved(device=None):
    """Bytes the allocator holds from the system (pool size / HBM limit)."""
    d = _resolve_device(device)
    stats = d.memory_stats() or {}
    for key in ("bytes_reserved", "pool_bytes", "bytes_limit"):
        if key in stats:
            return int(stats[key])
    return memory_allocated(device)


max_memory_reserved = memory_reserved


def empty_cache():
    """Release cached host-side references so XLA can reuse device memory
    (XLA's allocator frees buffers when their arrays are garbage-collected)."""
    import gc

    gc.collect()


class Stream:
    """XLA schedules its own streams; this exists for API parity and ordering is a no-op
    (all work on one device is program-ordered)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def query(self):
        return True


class Event:
    """Timing events: record() syncs the device then timestamps, so
    a.elapsed_time(b) measures real device wall-clock between the records
    (XLA-async safe). query/synchronize are immediate post-sync."""

    def __init__(self, enable_timing=True, blocking=False, interprocess=False):
        self._ts = None

    def record(self, stream=None):
        synchronize()
        import time

        self._ts = time.perf_counter()

    def query(self):
        return True

    def synchronize(self):
        synchronize()

    def elapsed_time(self, end_event):
        """Milliseconds between this record() and `end_event`'s record()."""
        if self._ts is None or end_event._ts is None:
            raise RuntimeError("both events must be recorded before elapsed_time")
        return (end_event._ts - self._ts) * 1e3


_current_stream = Stream()


def current_stream(device=None):
    return _current_stream


import contextlib


@contextlib.contextmanager
def stream_guard(stream):
    yield


class cuda:
    """paddle.device.cuda compat shim — maps to the accelerator device."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def current_stream(device=None):
        return _current_stream

    @staticmethod
    def max_memory_allocated(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("peak_bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def memory_allocated(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def max_memory_reserved(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("peak_bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def memory_reserved(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("bytes_limit", 0)
        except Exception:
            return 0

    @staticmethod
    def empty_cache():
        pass


class IPUPlace:
    """Reference: paddle.device.IPUPlace — accepted for script parity; no IPU
    backend exists here (the PJRT plugin ABI is the extension point)."""

    def __repr__(self):
        return "Place(ipu)"


def get_cudnn_version():
    """Reference: device/__init__.py — no CUDA stack on TPU builds."""
    return None


def is_compiled_with_cinn():
    """The Pallas kernel layer plays CINN's role (SURVEY §2 row 11); the CINN
    compiler itself is not part of this build."""
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_custom_device(device_name=None):
    """PJRT plugins are the custom-device mechanism: true iff a non-builtin
    platform is registered (e.g. the out-of-tree TPU tunnel plugin)."""
    import jax

    try:
        return jax.devices()[0].platform not in ("cpu", "gpu", "cuda")
    except Exception:
        return False


def is_compiled_with_distribute():
    return True  # jax.distributed + the store control plane always ship


def set_stream(stream=None):
    """Reference: device/__init__.py set_stream — XLA owns stream assignment;
    accepted and ignored (documented no-op, same as the Config stream knobs
    in inference/__init__.py)."""
    return stream
