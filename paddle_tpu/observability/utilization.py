"""Fleet utilization ledger (ISSUE-19): per-tick FLOPs attribution.

The continuous scheduler launches FIXED-WIDTH programs — prefill_chunk
[S, C], decode_step [S]xT, verify_step [S, K+1] — so every launch issues a
CONSTANT amount of compute regardless of how much of it serves live
tokens. Padding (idle slots, masked chunk tail, EOS-frozen rows), rejected
speculation and host gaps between launches are all invisible to the
existing token counters: a fleet can read "healthy tok/s" while most of
its FLOPs heat pad rows. This module makes the waste a first-class,
CONSERVED quantity:

    issued == useful + pad_waste + spec_waste        (exactly, per tick)
    sum(per-tenant billed) == useful                 (exactly)

Exactness is by construction, not by epsilon: all attribution happens in
INTEGER flops units. A launch's issued FLOPs (``observability/xla.py
cost_flops`` on the lowered step program, computed once per program cache
key) are split token-proportionally with floor division —
``useful_i = issued * units_i // total_units`` — and pad_waste absorbs
the rounding remainder, so the invariants above hold bit-exactly and the
conservation property sweep (tests/test_utilization.py) can assert ``==``
after every tick under mixed greedy/sampled/spec/preempted traffic.
Tenant bills are the SAME per-slot integers grouped by tenant, so the
chargeback sum closes on useful by construction too; preempted (paused)
sequences are off-slot and contribute no units, so paused time can never
bill a tenant.

Tick wall-time splits the same way: launch wall (the device-side work,
summed from the generation timing hook) vs HOST GAP (everything else the
tick spent on the host — admission bookkeeping, numpy assembly, absorb).
The gap histogram is the dispatch-efficiency dial ROADMAP's disaggregated
prefill/decode item needs before tiers can be sized.

Exported series (absent-iff-off, like every optional subsystem):

* ``paddle_serving_flops_total{component,kind}`` — kind in
  useful | pad | spec_waste; the sum over kinds is issued.
* ``paddle_tenant_flops_total{component,tenant}`` — chargeback counters.
* ``paddle_serving_host_gap_seconds{component}`` — per-tick histogram.
* ``paddle_serving_mfu{component}`` — rolling-window useful FLOP/s over
  ``device_peak_flops`` — registered only when the peak is KNOWN (real
  accelerator or an injected ``peak_flops=``); on CPU the gauge is absent,
  never a made-up number (same contract as training MFU).
"""
from __future__ import annotations

import collections
import threading
import time

from .xla import device_peak_flops

__all__ = ["UtilizationLedger", "attribute_launch", "HOST_GAP_BUCKETS"]

# per-tick host gaps are sub-millisecond on a healthy scheduler and spike
# to tens of ms when the host falls behind — finer-than-latency buckets
HOST_GAP_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                    0.025, 0.05, 0.1, 0.25, 0.5, 1.0)


def attribute_launch(flops, total_units, slot_units, spec_units=0):
    """Integer decomposition of one launch's issued FLOPs.

    ``slot_units``: iterable of ``(tenant_or_None, useful_units)`` — one
    entry per live slot. ``spec_units``: rejected draft positions across
    the launch. Returns ``(issued, useful, pad, spec, bills)`` where
    ``bills`` maps tenant name -> integer flops and every invariant holds
    exactly: ``issued == useful + pad + spec``, ``sum(bills) == useful``.

    Floor division can only UNDER-attribute each slot, so pad (the
    remainder) is always >= 0 as long as the caller's units fit the
    launch: ``sum(useful_units) + spec_units <= total_units``.
    """
    issued = max(0, int(round(flops or 0.0)))
    total = int(total_units)
    useful = 0
    bills: dict = {}
    spec = 0
    if issued > 0 and total > 0:
        for tenant, units in slot_units:
            units = int(units)
            if units <= 0:
                continue
            share = issued * units // total
            if share <= 0:
                continue
            useful += share
            key = "default" if tenant is None else str(tenant)
            bills[key] = bills.get(key, 0) + share
        spec = issued * int(spec_units) // total
    pad = issued - useful - spec
    return issued, useful, pad, spec, bills


class UtilizationLedger:
    """Per-tick FLOPs/wall decomposition for one continuous scheduler.

    The tick thread drives ``tick_begin`` / ``record_launch`` /
    ``tick_end``; gauges and the ``/utilization`` endpoint read
    ``snapshot()`` / ``last_tick`` from other threads (totals are guarded
    by a lock; in-tick accumulators are tick-thread-only).

    ``peak_flops``: MFU denominator (FLOP/s). Default resolves
    ``device_peak_flops`` of the first jax device — None on CPU, which
    leaves the MFU gauge unregistered (absent-iff-off). ``clock`` is
    injectable for deterministic tests.
    """

    def __init__(self, *, peak_flops=None, device=None,
                 clock=time.monotonic, mfu_window_s=10.0,
                 gap_samples=1024):
        if peak_flops is None:
            if device is None:
                try:
                    import jax

                    device = jax.devices()[0]
                except Exception:
                    device = None
            if device is not None:
                peak_flops = device_peak_flops(device)
        self.peak_flops = peak_flops
        self._clock = clock
        self.mfu_window_s = float(mfu_window_s)
        self._lock = threading.Lock()
        # lifetime totals (integer flops, exact)
        self.issued = 0
        self.useful = 0
        self.pad_waste = 0
        self.spec_waste = 0
        self.by_tenant: dict = {}
        self.ticks = 0
        self.launches = 0
        self.launch_wall_s = 0.0
        self.host_gap_s = 0.0
        self._gaps = collections.deque(maxlen=int(gap_samples))
        # MFU window: (t_end, tick_wall_s, useful_flops) per tick
        self._window: collections.deque = collections.deque()
        self.last_tick = None
        # in-tick state — tick thread only
        self._t0 = None
        self._tick = None
        # metric children, bound by bind_metrics (None = no registry)
        self._flops_counter = None
        self._tenant_counter = None
        self._gap_hist = None

    # ------------------------------------------------------------- metrics
    def bind_metrics(self, registry, component="continuous"):
        """Register the utilization series on ``registry``. The MFU gauge
        binds only when ``peak_flops`` is known — a denominator-less MFU
        would be a made-up number, so on CPU the series is simply absent."""
        self._component = component
        self._flops_counter = registry.counter(
            "paddle_serving_flops_total",
            "Issued step-program FLOPs decomposed by kind; conservation: "
            "useful + pad + spec_waste == issued (exact, integer units)",
            labels=("component", "kind"))
        self._tenant_counter = registry.counter(
            "paddle_tenant_flops_total",
            "Useful FLOPs billed per tenant (chargeback); the sum over "
            "tenants equals the useful kind exactly — paused sequences "
            "are off-slot and never billed",
            labels=("component", "tenant"))
        self._gap_hist = registry.histogram(
            "paddle_serving_host_gap_seconds",
            "Per-tick host time outside step-program launches (tick wall "
            "minus launch wall) — the dispatch-efficiency dial",
            labels=("component",), buckets=HOST_GAP_BUCKETS).labels(
                component)
        if self.peak_flops:
            registry.gauge(
                "paddle_serving_mfu",
                "Serving model FLOPs utilization: rolling-window USEFUL "
                "FLOP/s over device_peak_flops (pad and rejected "
                "speculation excluded — the honest utilization number)",
                labels=("component",)).labels(component).set_function(
                    self.mfu)
        return self

    # ------------------------------------------------------------ tick API
    def tick_begin(self):
        self._t0 = self._clock()
        self._tick = {
            "issued": 0, "useful": 0, "pad": 0, "spec_waste": 0,
            "launch_s": 0.0, "tenants": {}, "programs": {},
        }

    def record_launch(self, program, flops, launch_s, total_units,
                      slot_units, spec_units=0):
        """Attribute one launch inside the current tick. ``slot_units`` is
        ``[(tenant_or_None, useful_units), ...]`` per live slot — the
        scheduler's ground truth of which positions carried live tokens."""
        if self._tick is None:      # launch outside a tick (warmup): skip
            return
        issued, useful, pad, spec, bills = attribute_launch(
            flops, total_units, slot_units, spec_units)
        t = self._tick
        t["issued"] += issued
        t["useful"] += useful
        t["pad"] += pad
        t["spec_waste"] += spec
        t["launch_s"] += float(launch_s or 0.0)
        for tenant, share in bills.items():
            t["tenants"][tenant] = t["tenants"].get(tenant, 0) + share
        p = t["programs"].setdefault(
            program, {"issued": 0, "useful": 0, "pad": 0, "spec_waste": 0,
                      "launches": 0})
        p["issued"] += issued
        p["useful"] += useful
        p["pad"] += pad
        p["spec_waste"] += spec
        p["launches"] += 1

    def tick_end(self):
        if self._tick is None:
            return None
        t, self._tick = self._tick, None
        now = self._clock()
        wall = max(0.0, now - (self._t0 if self._t0 is not None else now))
        self._t0 = None
        gap = max(0.0, wall - t["launch_s"])
        t["wall_s"] = wall
        t["host_gap_s"] = gap
        launches = sum(p["launches"] for p in t["programs"].values())
        with self._lock:
            self.issued += t["issued"]
            self.useful += t["useful"]
            self.pad_waste += t["pad"]
            self.spec_waste += t["spec_waste"]
            for tenant, share in t["tenants"].items():
                self.by_tenant[tenant] = (self.by_tenant.get(tenant, 0)
                                          + share)
            self.ticks += 1
            self.launches += launches
            self.launch_wall_s += t["launch_s"]
            self.host_gap_s += gap
            self._gaps.append(gap)
            self._window.append((now, wall, t["useful"]))
            self._prune_window(now)
            self.last_tick = t
        if self._flops_counter is not None:
            c = self._flops_counter
            c.labels(self._component, "useful").inc(t["useful"])
            c.labels(self._component, "pad").inc(t["pad"])
            c.labels(self._component, "spec_waste").inc(t["spec_waste"])
            for tenant, share in t["tenants"].items():
                self._tenant_counter.labels(
                    self._component, tenant).inc(share)
            self._gap_hist.observe(gap)
        return t

    def _prune_window(self, now):
        horizon = now - self.mfu_window_s
        w = self._window
        while w and w[0][0] < horizon:
            w.popleft()

    # ------------------------------------------------------------- reading
    def mfu(self):
        """Rolling-window useful FLOP/s over peak (0.0 with no peak or no
        ticks in the window). Elapsed time spans from the oldest retained
        tick's BEGIN to now, so a single tick reads its own wall."""
        if not self.peak_flops:
            return 0.0
        now = self._clock()
        with self._lock:
            self._prune_window(now)
            if not self._window:
                return 0.0
            t_end0, wall0, _ = self._window[0]
            elapsed = max(1e-9, now - (t_end0 - wall0))
            useful = sum(u for _, _, u in self._window)
        return useful / (elapsed * self.peak_flops)

    @staticmethod
    def _pct(sorted_vals, q):
        if not sorted_vals:
            return None
        i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
        return sorted_vals[i]

    def snapshot(self) -> dict:
        """Full JSON state for ``/utilization``: lifetime totals (integer
        flops, conservation checkable by the reader), per-tenant bills,
        host-gap percentiles and the last tick's decomposition."""
        with self._lock:
            gaps = sorted(self._gaps)
            out = {
                "flops": {
                    "issued": self.issued, "useful": self.useful,
                    "pad_waste": self.pad_waste,
                    "spec_waste": self.spec_waste,
                },
                "tenants": dict(self.by_tenant),
                "ticks": self.ticks,
                "launches": self.launches,
                "launch_wall_s": round(self.launch_wall_s, 6),
                "host_gap_s": round(self.host_gap_s, 6),
                "last_tick": self.last_tick,
            }
        if self.issued:
            out["useful_ratio"] = round(self.useful / self.issued, 6)
        for q, name in ((0.50, "host_gap_p50_s"), (0.99, "host_gap_p99_s")):
            v = self._pct(gaps, q)
            if v is not None:
                out[name] = round(v, 6)
        out["peak_flops"] = self.peak_flops
        out["mfu"] = round(self.mfu(), 6) if self.peak_flops else None
        return out

    def metrics_block(self) -> dict:
        """Compact block for the JSON /metrics snapshot (mirrors the PR 18
        tracer/flight blocks): mfu, flops by kind, host-gap tail."""
        snap = self.snapshot()
        return {
            "mfu": snap["mfu"],
            "flops": snap["flops"],
            "host_gap_p50_s": snap.get("host_gap_p50_s"),
            "host_gap_p99_s": snap.get("host_gap_p99_s"),
        }
