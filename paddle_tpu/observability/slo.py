"""Serving SLOs: declarative objectives, rolling windows, burn-rate alerting.

Reference role: the Google SRE-workbook multi-window multi-burn-rate
alerting recipe, applied to the serving stack's phase-attributed latency
series (scheduler TTFT/TPOT, ISSUE-18). An SLO here is "fraction of GOOD
events >= target over a rolling window"; latency objectives translate the
standard way — ``ttft_p95_ms: 200`` means "at most 5% of requests may take
longer than 200ms to first token", i.e. good = (ttft <= 200ms) with
target 0.95 — so every objective reduces to one good/bad event stream.

Definitions (pinned by tests/test_slo_observability.py):

* ``bad_fraction(W)``   — bad events / total events over the last W seconds
  (0.0 with no events: an idle service burns no budget).
* ``burn_rate(W)``      — bad_fraction(W) / (1 - target). Burn rate 1.0
  sustained for the whole budget window consumes exactly the error budget;
  14.4 empties a 30-day budget in ~2 days (the SRE-workbook page numbers).
* ``error_budget_remaining`` — max(0, 1 - burn_rate(slow)): the fraction of
  the slow (budget) window's error budget still unspent.
* ``state`` — "alerting" iff BOTH windows burn >= ``burn_threshold``
  (fast = is it happening NOW, slow = has it been happening long enough to
  matter), "fast_burn" when only the fast window is hot (a blip that has
  not yet consumed meaningful budget), else "ok". Requiring both windows is
  what keeps a 2-second latency spike from paging anyone while a sustained
  regression still alerts within the fast window's span.

``SLOMonitor`` composes policies, routes scheduler observations to them by
kind, exports ``paddle_slo_error_budget_remaining{slo}`` and
``paddle_slo_burn_rate{slo,window=fast|slow}`` gauges, fires registered
``on_alert`` callbacks exactly on the not-alerting -> alerting edge (the
scheduler wires the flight recorder's dump there), and serves the
``/slo`` endpoint's JSON snapshot. Clocks are injectable everywhere —
the burn-rate lifecycle tests drive a fake clock through
budget-exhaust -> fast alert -> slow confirm -> recovery without sleeping.
"""
from __future__ import annotations

import collections
import re
import threading
import time

__all__ = ["SLOPolicy", "SLOMonitor", "make_policies"]

# objective key grammar: ttft_p95_ms / tpot_p99_ms / tpot_p99.9_ms
_LATENCY_KEY = re.compile(r"^(ttft|tpot)_p(\d+(?:\.\d+)?)_ms$")


class SLOPolicy:
    """One objective as a good-event fraction over fast/slow rolling windows.

    kind         "ttft" | "tpot" | "availability" — which scheduler
                 observation stream feeds this policy.
    target       required good fraction (0 < target < 1), e.g. 0.95 for a
                 p95 latency objective or 0.999 for three-nines availability.
    threshold_ms latency kinds only: an observation is GOOD iff
                 value <= threshold_ms.
    fast/slow    rolling window spans in seconds (fast < slow); slow doubles
                 as the error-budget window.
    burn_threshold  both windows' burn rate must reach this for "alerting".
    clock        injectable monotonic clock (seconds).
    max_events   ring bound on retained events (memory cap; oldest evicted).
    """

    __slots__ = ("name", "kind", "target", "threshold_ms", "fast_window_s",
                 "slow_window_s", "burn_threshold", "_clock", "_events",
                 "_lock", "_alerting", "total_events", "bad_events")

    def __init__(self, name, kind, target, threshold_ms=None,
                 fast_window_s=60.0, slow_window_s=300.0,
                 burn_threshold=2.0, clock=time.monotonic, max_events=16384):
        if kind not in ("ttft", "tpot", "availability"):
            raise ValueError(f"unknown SLO kind {kind!r} "
                             "(ttft | tpot | availability)")
        if not 0.0 < float(target) < 1.0:
            raise ValueError(f"SLO {name!r}: target must be in (0, 1) — "
                             "an exact-1.0 objective has no error budget "
                             "to burn")
        if kind in ("ttft", "tpot") and threshold_ms is None:
            raise ValueError(f"SLO {name!r}: latency kind {kind!r} needs "
                             "threshold_ms")
        if not float(fast_window_s) < float(slow_window_s):
            raise ValueError(f"SLO {name!r}: fast window must be shorter "
                             "than the slow (budget) window")
        self.name = str(name)
        self.kind = kind
        self.target = float(target)
        self.threshold_ms = (None if threshold_ms is None
                             else float(threshold_ms))
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self._clock = clock
        # (t, good) ring; pruned to the slow window on every write/read
        self._events: collections.deque = collections.deque(
            maxlen=int(max_events))
        self._lock = threading.Lock()
        self._alerting = False          # edge detection (SLOMonitor)
        self.total_events = 0           # lifetime, for the snapshot
        self.bad_events = 0

    # -------------------------------------------------------------- recording
    def record(self, good, t=None):
        """One good/bad event (availability kind, or pre-thresholded)."""
        now = self._clock() if t is None else float(t)
        with self._lock:
            self._events.append((now, bool(good)))
            self.total_events += 1
            if not good:
                self.bad_events += 1
            self._prune(now)

    def observe(self, value_s, t=None):
        """One latency observation (seconds); thresholded to good/bad."""
        if self.threshold_ms is None:
            raise ValueError(f"SLO {self.name!r} has no latency threshold")
        self.record(float(value_s) * 1000.0 <= self.threshold_ms, t=t)

    def _prune(self, now):
        # under self._lock; drop events older than the slow (budget) window
        horizon = now - self.slow_window_s
        ev = self._events
        while ev and ev[0][0] < horizon:
            ev.popleft()

    # ------------------------------------------------------------------ math
    def bad_fraction(self, window_s) -> float:
        now = self._clock()
        horizon = now - float(window_s)
        with self._lock:
            self._prune(now)
            total = bad = 0
            for t, good in self._events:
                if t < horizon:
                    continue
                total += 1
                if not good:
                    bad += 1
        return bad / total if total else 0.0

    def _window_s(self, window) -> float:
        if window == "fast":
            return self.fast_window_s
        if window == "slow":
            return self.slow_window_s
        raise ValueError(f"unknown window {window!r} (fast | slow)")

    def burn_rate(self, window) -> float:
        """Error-budget burn rate over one window: bad_fraction / budget."""
        budget = 1.0 - self.target
        return self.bad_fraction(self._window_s(window)) / budget

    def error_budget_remaining(self) -> float:
        """Unspent fraction of the slow window's error budget, floored at 0
        (a gauge that goes negative reads as a scrape bug, not "more than
        everything is spent")."""
        return max(0.0, 1.0 - self.burn_rate("slow"))

    def state(self) -> str:
        """"alerting" (both windows hot) | "fast_burn" (blip) | "ok"."""
        fast_hot = self.burn_rate("fast") >= self.burn_threshold
        slow_hot = self.burn_rate("slow") >= self.burn_threshold
        if fast_hot and slow_hot:
            return "alerting"
        if fast_hot:
            return "fast_burn"
        return "ok"

    def snapshot(self) -> dict:
        return {
            "kind": self.kind,
            "target": self.target,
            "threshold_ms": self.threshold_ms,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "burn_threshold": self.burn_threshold,
            "burn_rate_fast": round(self.burn_rate("fast"), 4),
            "burn_rate_slow": round(self.burn_rate("slow"), 4),
            "error_budget_remaining": round(self.error_budget_remaining(), 4),
            "state": self.state(),
            "total_events": self.total_events,
            "bad_events": self.bad_events,
        }


def make_policies(objectives, *, fast_window_s=60.0, slow_window_s=300.0,
                  burn_threshold=2.0, clock=time.monotonic):
    """Declarative objectives -> [SLOPolicy].

    ``objectives`` maps objective keys to their thresholds/targets::

        make_policies({"ttft_p95_ms": 200.0,   # p95 TTFT <= 200ms
                       "tpot_p99_ms": 50.0,    # p99 TPOT <= 50ms
                       "availability": 0.999}) # non-5xx terminal fraction

    ``<kind>_p<q>_ms: X`` becomes kind=<kind>, target=q/100,
    threshold_ms=X (the standard percentile-to-good-fraction translation);
    ``availability: t`` becomes kind="availability", target=t."""
    policies = []
    for key, value in objectives.items():
        m = _LATENCY_KEY.match(key)
        if m is not None:
            kind, q = m.group(1), float(m.group(2))
            if not 0.0 < q < 100.0:
                raise ValueError(f"objective {key!r}: percentile out of "
                                 "range (0, 100)")
            policies.append(SLOPolicy(
                key, kind, target=q / 100.0, threshold_ms=float(value),
                fast_window_s=fast_window_s, slow_window_s=slow_window_s,
                burn_threshold=burn_threshold, clock=clock))
        elif key == "availability":
            policies.append(SLOPolicy(
                key, "availability", target=float(value),
                fast_window_s=fast_window_s, slow_window_s=slow_window_s,
                burn_threshold=burn_threshold, clock=clock))
        else:
            raise ValueError(
                f"unknown SLO objective {key!r} (ttft_p<q>_ms | "
                "tpot_p<q>_ms | availability)")
    return policies


class SLOMonitor:
    """Policy set + gauge export + alert-edge callbacks + /slo snapshot.

    Built either from declarative ``objectives`` (see ``make_policies``) or
    explicit ``policies``. The scheduler feeds it at retirement
    (``observe_ttft`` / ``observe_tpot``) and at every terminal CAS
    (``observe_terminal``); each feed re-evaluates states and fires
    ``on_alert`` callbacks exactly on a policy's not-alerting -> alerting
    edge (re-armed when the policy recovers). Callbacks run on the feeding
    thread (usually the scheduler tick loop) and are exception-isolated —
    a broken alert hook must never take a tick down."""

    def __init__(self, objectives=None, policies=None,
                 fast_window_s=60.0, slow_window_s=300.0,
                 burn_threshold=2.0, clock=time.monotonic):
        self.policies = list(policies) if policies is not None else []
        if objectives:
            self.policies.extend(make_policies(
                objectives, fast_window_s=fast_window_s,
                slow_window_s=slow_window_s, burn_threshold=burn_threshold,
                clock=clock))
        if not self.policies:
            raise ValueError("SLOMonitor needs at least one objective")
        names = [p.name for p in self.policies]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO policy names: {names}")
        self._clock = clock
        self._callbacks: list = []
        self._bound_registries: set = set()
        self._bind_lock = threading.Lock()
        # newest-first breach context for the /slo snapshot and runbook:
        # (t, policy, kind, tenant) of recent BAD events (atomic deque)
        self.recent_bad: collections.deque = collections.deque(maxlen=32)

    # --------------------------------------------------------------- feeding
    def observe_ttft(self, seconds, tenant=None):
        self._feed("ttft", value_s=seconds, tenant=tenant)

    def observe_tpot(self, seconds, tenant=None):
        self._feed("tpot", value_s=seconds, tenant=tenant)

    def observe_terminal(self, good, tenant=None):
        self._feed("availability", good=bool(good), tenant=tenant)

    def _feed(self, kind, value_s=None, good=None, tenant=None):
        for p in self.policies:
            if p.kind != kind:
                continue
            if kind == "availability":
                is_good = good
                p.record(is_good)
            else:
                is_good = float(value_s) * 1000.0 <= p.threshold_ms
                p.record(is_good)
            if not is_good:
                self.recent_bad.append(
                    (self._clock(), p.name, kind, tenant))
        self._check_alerts()

    def _check_alerts(self):
        for p in self.policies:
            alerting = p.state() == "alerting"
            was = p._alerting
            p._alerting = alerting
            if alerting and not was:
                for cb in list(self._callbacks):
                    try:
                        cb(p)
                    except Exception:   # noqa: BLE001 — isolation contract
                        pass

    def on_alert(self, fn):
        """Register fn(policy) for the not-alerting -> alerting edge."""
        self._callbacks.append(fn)
        return fn

    def alerting(self) -> list:
        """Names of currently-alerting policies (both windows hot)."""
        return [p.name for p in self.policies if p.state() == "alerting"]

    # --------------------------------------------------------------- metrics
    def bind_metrics(self, registry):
        """Export the SLO gauges on `registry` (idempotent per registry —
        fleet replicas sharing one monitor and one registry bind once).
        Gauges exist only when a policy is installed: the exposition-lint
        contract is "paddle_slo_* present IFF an SLOMonitor is wired"."""
        with self._bind_lock:
            if id(registry) in self._bound_registries:
                return
            self._bound_registries.add(id(registry))
        budget = registry.gauge(
            "paddle_slo_error_budget_remaining",
            "Unspent fraction of the slow-window error budget by SLO "
            "(1.0 = untouched, 0.0 = exhausted)", labels=("slo",))
        burn = registry.gauge(
            "paddle_slo_burn_rate",
            "Error-budget burn rate by SLO and window (SRE multi-window "
            "multi-burn-rate: 'alerting' needs both windows over the "
            "policy's burn_threshold)", labels=("slo", "window"))
        for p in self.policies:
            budget.labels(p.name).set_function(
                lambda p=p: p.error_budget_remaining())
            for w in ("fast", "slow"):
                burn.labels(p.name, w).set_function(
                    lambda p=p, w=w: p.burn_rate(w))

    # -------------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        """JSON shape of the /slo endpoint."""
        return {
            "alerting": self.alerting(),
            "policies": {p.name: p.snapshot() for p in self.policies},
            "recent_bad": [
                {"t": round(t, 6), "slo": name, "kind": kind,
                 "tenant": tenant}
                for t, name, kind, tenant in list(self.recent_bad)
            ],
        }
