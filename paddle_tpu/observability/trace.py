"""Request-scoped tracing for the serving runtime (Dapper-style spans).

Reference role: the request-causality half of production LLM observability —
OpenTelemetry-style trace/span ids joined to the host profiler
(paddle_tpu/profiler/profiler.py) on ONE timebase, so "where did this 504
spend its deadline" is answerable from a single chrome-trace view instead of
three disjoint logs.

Design:

* ``Tracer`` — a bounded ring buffer of finished ``Span``s on an injectable
  clock.  The default clock is ``time.perf_counter`` — the SAME clock the
  profiler's host events use (``time.perf_counter_ns``/1e3), so tracer spans
  and profiler events interleave correctly in a merged chrome trace without
  any offset arithmetic.
* contextvar propagation — ``tracer.span(...)`` nests through
  ``contextvars``, so single-threaded instrumentation needs no plumbing.
  The serving path crosses threads (HTTP handler → queue → batcher), where
  contextvars do NOT flow; ``RequestTrace`` carries the (trace_id, root
  span) pair on the request object instead and records spans from whichever
  thread observed the interval.
* sampling — ``sample_rate`` decides per TRACE (at root creation), never per
  span, so a sampled trace is always complete.  ``enabled=False`` turns the
  whole tracer into no-ops (the ``observability_overhead`` bench leg measures
  exactly this on/off delta).
* export — ``export_chrome`` emits complete "X" events; ``export_joined_chrome``
  merges tracer spans with a Profiler's host events, sorted by ``ts``.

Span taxonomy for the serving lifecycle is documented in
docs/OBSERVABILITY.md and pinned by tests/test_observability.py.
"""
from __future__ import annotations

import contextvars
import itertools
import json
import os
import random
import threading
import time
from collections import deque
from contextlib import contextmanager

__all__ = ["Span", "Tracer", "RequestTrace", "new_trace_id",
           "current_trace_id", "export_joined_chrome"]

# (trace_id, span_id) of the innermost open span in THIS context
_ctx: contextvars.ContextVar = contextvars.ContextVar("paddle_trace_ctx",
                                                      default=None)

_session = f"{os.getpid() & 0xFFFF:04x}{random.SystemRandom().randrange(16 ** 4):04x}"
_trace_seq = itertools.count(1)


def new_trace_id() -> str:
    """Process-unique trace id: <pid+rand session>-<sequence>."""
    return f"{_session}-{next(_trace_seq):08x}"


def current_trace_id():
    """Trace id of the innermost open contextvar span, or None."""
    cur = _ctx.get()
    return cur[0] if cur is not None else None


class Span:
    """One finished (closed) interval in a trace."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name",
                 "start_us", "end_us", "tid", "tags")

    def __init__(self, trace_id, span_id, parent_id, name,
                 start_us, end_us, tid, tags):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start_us = float(start_us)
        self.end_us = float(end_us)
        self.tid = tid
        self.tags = tags or {}

    @property
    def duration_us(self) -> float:
        return self.end_us - self.start_us

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"Span({self.name!r}, trace={self.trace_id}, "
                f"dur={self.duration_us:.1f}us, tags={self.tags})")


class Tracer:
    """Ring-buffer span store on an injectable clock.

    ``capacity`` bounds memory: the newest ``capacity`` spans are retained,
    older ones are dropped (counted in ``dropped``) — a tracer left on in a
    long-running server can never grow without bound.
    """

    def __init__(self, capacity=4096, clock=time.perf_counter,
                 sample_rate=1.0, enabled=True, rng=None):
        self.clock = clock
        self.enabled = bool(enabled)
        self.sample_rate = float(sample_rate)
        self._rng = rng if rng is not None else random.Random(0x5EED)
        self._spans: deque[Span] = deque(maxlen=int(capacity))
        self._span_seq = itertools.count(1)
        self._lock = threading.Lock()
        self._recorded = 0

    # ------------------------------------------------------------------ time
    def now_us(self) -> float:
        """Current time in microseconds on the tracer clock (profiler-joined
        timebase when the default perf_counter clock is kept)."""
        return self.clock() * 1e6

    # ------------------------------------------------------------- decisions
    def should_sample(self) -> bool:
        """Per-TRACE sampling decision (call once at root creation)."""
        if not self.enabled:
            return False
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        return self._rng.random() < self.sample_rate

    def new_span_id(self) -> str:
        return f"s{next(self._span_seq):06x}"

    # --------------------------------------------------------------- storage
    def record(self, name, start_us, end_us, trace_id, parent_id=None,
               span_id=None, tags=None, tid=None):
        """Record a closed span with explicit timestamps (the cross-thread
        path: the caller observed the interval, whichever thread that was).
        Returns the span id."""
        if not self.enabled:
            return None
        sid = span_id or self.new_span_id()
        span = Span(trace_id, sid, parent_id, name, start_us,
                    max(end_us, start_us),
                    tid if tid is not None else threading.get_ident(),
                    dict(tags) if tags else {})
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                pass  # deque evicts the oldest on append
            self._recorded += 1
            self._spans.append(span)
        return sid

    @property
    def dropped(self) -> int:
        """Spans evicted from the ring buffer so far."""
        with self._lock:
            return max(0, self._recorded - len(self._spans))

    def clear(self):
        with self._lock:
            self._spans.clear()
            self._recorded = 0

    # ------------------------------------------------------------- retrieval
    def spans(self) -> list:
        with self._lock:
            return list(self._spans)

    def trace(self, trace_id) -> list:
        """All retained spans of one trace, in interval-containment order
        (by start time, enclosing spans before the spans they contain)."""
        return sorted((s for s in self.spans() if s.trace_id == trace_id),
                      key=lambda s: (s.start_us, -s.end_us))

    def trace_ids(self) -> list:
        seen: dict = {}
        for s in self.spans():
            seen.setdefault(s.trace_id, None)
        return list(seen)

    # ------------------------------------------------------------ contextvar
    @contextmanager
    def span(self, name, trace_id=None, **tags):
        """Contextvar-nested span for single-threaded instrumentation::

            with tracer.span("load"):
                with tracer.span("read_shard", shard=3):
                    ...

        A new trace id is minted when there is no enclosing span and none is
        passed. Exceptions are tagged (``error=repr(exc)``) and re-raised."""
        cur = _ctx.get()
        if trace_id is None:
            trace_id = cur[0] if cur is not None else new_trace_id()
        parent_id = cur[1] if (cur is not None and cur[0] == trace_id) else None
        sid = self.new_span_id()
        token = _ctx.set((trace_id, sid))
        start = self.now_us()
        try:
            yield trace_id
        except BaseException as e:
            tags = dict(tags)
            tags["error"] = repr(e)
            raise
        finally:
            _ctx.reset(token)
            self.record(name, start, self.now_us(), trace_id,
                        parent_id=parent_id, span_id=sid, tags=tags)

    # ---------------------------------------------------------------- export
    def chrome_events(self) -> list:
        """Complete-event ("X") dicts on the shared profiler timebase."""
        pid = os.getpid()
        out = []
        for s in self.spans():
            args = {"trace_id": s.trace_id, "span_id": s.span_id}
            if s.parent_id:
                args["parent_id"] = s.parent_id
            args.update(s.tags)
            out.append({"name": s.name, "ph": "X", "cat": "serving",
                        "ts": s.start_us, "dur": s.duration_us,
                        "pid": pid, "tid": s.tid, "args": args})
        out.sort(key=lambda e: e["ts"])
        return out

    def export_chrome(self, path=None):
        """Write (or return) a chrome://tracing JSON of all retained spans."""
        doc = {"traceEvents": self.chrome_events(), "displayTimeUnit": "ms"}
        if path is None:
            return doc
        with open(path, "w") as f:
            json.dump(doc, f)
        return path


class RequestTrace:
    """Trace handle that rides a serving request across threads.

    contextvars do not flow HTTP-handler → queue → batcher thread, so the
    request object carries this instead: the root span opens at admission,
    children are recorded (with explicit timestamps) by whichever thread
    observed the interval, and exactly one ``finish(outcome)`` closes the
    root — mirroring the PR 2 terminal-outcome CAS, whose winner tags the
    terminal span."""

    __slots__ = ("tracer", "trace_id", "root_id", "t0_us", "_done")

    def __init__(self, tracer, trace_id=None, sampled=None):
        if sampled is None:
            sampled = tracer.should_sample() if tracer is not None else False
        self.tracer = tracer if (tracer is not None and sampled
                                 and tracer.enabled) else None
        self.trace_id = trace_id or new_trace_id()
        self.root_id = (self.tracer.new_span_id()
                        if self.tracer is not None else None)
        self.t0_us = self.tracer.now_us() if self.tracer is not None else 0.0
        self._done = False

    @property
    def sampled(self) -> bool:
        return self.tracer is not None

    def now_us(self) -> float:
        return self.tracer.now_us() if self.tracer is not None else 0.0

    def child(self, name, start_us, end_us, **tags):
        """Record a closed child-of-root span from explicit timestamps."""
        if self.tracer is None:
            return
        self.tracer.record(name, start_us, end_us, self.trace_id,
                           parent_id=self.root_id, tags=tags)

    def event(self, name, **tags):
        """Zero-duration point event under the root span."""
        if self.tracer is None:
            return
        t = self.tracer.now_us()
        self.tracer.record(name, t, t, self.trace_id,
                           parent_id=self.root_id, tags=tags)

    @contextmanager
    def span(self, name, **tags):
        """Child span over a with-block (same-thread intervals)."""
        if self.tracer is None:
            yield self
            return
        start = self.tracer.now_us()
        try:
            yield self
        finally:
            self.child(name, start, self.tracer.now_us(), **tags)

    def finish(self, outcome, **tags):
        """Terminal: record the outcome-tagged terminal span and close the
        root. Idempotent — only the first caller (the CAS winner's path)
        records; later calls are no-ops."""
        if self.tracer is None or self._done:
            return False
        self._done = True
        end = self.tracer.now_us()
        self.tracer.record(outcome, end, end, self.trace_id,
                           parent_id=self.root_id,
                           tags={"outcome": outcome, **tags})
        self.tracer.record("request", self.t0_us, end, self.trace_id,
                           span_id=self.root_id,
                           tags={"outcome": outcome, **tags})
        return True


def export_joined_chrome(path, tracer=None, profiler=None, extra_events=()):
    """Merge tracer spans and profiler HOST events into one chrome trace.

    Both sides timestamp with ``time.perf_counter`` microseconds (the tracer
    by default, the profiler always), so the merged view needs no clock
    alignment: serving spans, model RecordEvents and ProfileStep markers land
    on one shared timeline. Device-side traces captured by ``jax.profiler``
    live in TensorBoard/perfetto format next to this file — join them by the
    wall-clock anchor tag documented in docs/OBSERVABILITY.md."""
    events = []
    if tracer is not None:
        events.extend(tracer.chrome_events())
    if profiler is not None:
        events.extend(profiler.chrome_events())
    events.extend(extra_events)
    events.sort(key=lambda e: e.get("ts", 0.0))
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if path is None:
        return doc
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
