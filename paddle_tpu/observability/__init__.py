"""paddle_tpu.observability: unified serving AND training observability.

Three parts, one timebase:

* ``trace`` — request-scoped Dapper-style spans (contextvar propagation for
  single-threaded code, ``RequestTrace`` handles for the cross-thread
  serving path), ring-buffer storage, chrome-trace export that interleaves
  with the host profiler's events (``paddle_tpu/profiler``) because both
  stamp ``time.perf_counter`` microseconds.
* ``metrics`` — typed Counter/Gauge/Histogram registry with labels and
  Prometheus text exposition; ``inference.resilience.ServingMetrics`` is
  re-based on it, and ``InferenceServer`` serves it at
  ``/metrics?format=prom``.
* ``training`` + ``xla`` — the training-side twin: a ``StepMonitor`` bound
  to ``jit/train.py:TrainStep`` emits per-step wall/throughput, live MFU
  from the compiled program's own ``cost_analysis()``, HBM watermarks from
  ``memory_analysis()``, a recompilation sentinel over argument avals, and
  typed numerics anomalies — all as ``paddle_train_*`` series on the same
  registry/tracer primitives (and the same perf_counter timebase, so
  ``export_joined_chrome`` shows step phases against profiler events).

Serving SLOs ride on the same registry: ``slo`` evaluates declarative
objectives (TTFT/TPOT percentiles, availability) over injectable-clock
rolling windows with SRE-workbook multi-window burn-rate alerting, and
``flightrecorder`` keeps a bounded ring of per-tick scheduler snapshots
dumped on demand (``/debug/ticks``), on alert, or on chaos-test failure.
``utilization`` closes the loop on the serving side of MFU: a
``UtilizationLedger`` decomposes every tick's issued step-program FLOPs
into useful / pad / spec-waste with exact integer conservation, bills
useful FLOPs per tenant, splits tick wall into launch vs host gap, and
exports ``paddle_serving_mfu`` from the same ``xla`` peak table training
uses (``/utilization`` serves the JSON view).

Span taxonomy, metric names and the scrape/join recipes live in
docs/OBSERVABILITY.md.
"""
from .flightrecorder import (  # noqa: F401
    FlightRecorder,
    dump_all,
    live_recorders,
)
from .metrics import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
    render_prometheus,
)
from .slo import (  # noqa: F401
    SLOMonitor,
    SLOPolicy,
    make_policies,
)
from .trace import (  # noqa: F401
    RequestTrace,
    Span,
    Tracer,
    current_trace_id,
    export_joined_chrome,
    new_trace_id,
)
from .training import (  # noqa: F401
    AnomalyEvent,
    NumericsAnomalyDetector,
    StepMonitor,
)
from .utilization import (  # noqa: F401
    UtilizationLedger,
    attribute_launch,
)
from .xla import (  # noqa: F401
    cost_flops,
    device_peak_flops,
    memory_stats,
)
