"""paddle_tpu.observability: unified serving observability.

Two halves, one timebase:

* ``trace`` — request-scoped Dapper-style spans (contextvar propagation for
  single-threaded code, ``RequestTrace`` handles for the cross-thread
  serving path), ring-buffer storage, chrome-trace export that interleaves
  with the host profiler's events (``paddle_tpu/profiler``) because both
  stamp ``time.perf_counter`` microseconds.
* ``metrics`` — typed Counter/Gauge/Histogram registry with labels and
  Prometheus text exposition; ``inference.resilience.ServingMetrics`` is
  re-based on it, and ``InferenceServer`` serves it at
  ``/metrics?format=prom``.

Span taxonomy, metric names and the scrape/join recipes live in
docs/OBSERVABILITY.md.
"""
from .metrics import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
    render_prometheus,
)
from .trace import (  # noqa: F401
    RequestTrace,
    Span,
    Tracer,
    current_trace_id,
    export_joined_chrome,
    new_trace_id,
)
