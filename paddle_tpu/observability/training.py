"""Training-side telemetry: StepMonitor over TrainStep.

The serving path answers "where did this request spend its deadline"
(trace.py + serving.py); this module makes the TRAINING path answer the
equivalent three questions live, per step, instead of offline in bench.py:

1. **How fast am I actually going?** — per-step wall time, samples/sec,
   tokens/sec, and live MFU whose numerator is the compiled program's OWN
   ``cost_analysis()`` FLOPs (``observability.xla``) — the same number
   bench.py audits, so the two cannot drift apart silently.
2. **Did I just recompile?** — a recompilation sentinel fingerprints the
   argument avals each ``TrainStep.__call__`` sees. A fingerprint never seen
   before (after the first compile) means XLA built a new program: counted in
   ``paddle_train_recompiles_total{reason=new_shape|aot_fallback}`` and
   trace-evented, including the AOT-executable fallback path where a
   shape-changed batch silently abandons the primed executable.
3. **Are my numerics still sane?** — a ``NumericsAnomalyDetector`` checks
   the fetched loss (and any grad norm the caller feeds it) for NaN/Inf and
   order-of-magnitude spikes against a rolling median; anomalies become
   typed events, counters, and trace points.

Integration shape: ``monitor.bind(step)`` attaches to a live
``jit/train.py:TrainStep`` — the step calls back into the monitor at three
points (begin / pre-launch / end), so instrumentation lives HERE and the hot
path pays three attribute checks when no monitor is bound.  Spans
(``data_wait → h2d → step → callbacks``) are recorded on the tracer's
default ``time.perf_counter`` timebase — the profiler's timebase — so
``export_joined_chrome`` shows host step phases against profiler events.

Everything streams through the PR 3 primitives: a ``MetricsRegistry`` (the
``paddle_train_*`` series, renderable next to the serving registries by
``render_prometheus``) and a ``Tracer``; an optional
``utils.log_writer.LogWriter`` sink mirrors the scalar series to the
VisualDL-role log.  Taxonomy and recipes: docs/OBSERVABILITY.md.
"""
from __future__ import annotations

import math
import statistics
import time
from collections import deque
from contextlib import contextmanager

from .metrics import MetricsRegistry
from .trace import Tracer, new_trace_id
from .xla import cost_flops, device_peak_flops, memory_stats

__all__ = ["StepMonitor", "NumericsAnomalyDetector", "AnomalyEvent",
           "TRAIN_STEP_BUCKETS"]

# step wall-time buckets: sub-ms eager smoke steps .. minute-long scans
TRAIN_STEP_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                      0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class AnomalyEvent:
    """One typed numerics anomaly: ``kind`` ∈ nan_loss | inf_loss |
    loss_spike | nan_grad_norm | inf_grad_norm | grad_norm_spike."""

    __slots__ = ("kind", "step", "value", "threshold")

    def __init__(self, kind, step, value, threshold=None):
        self.kind = kind
        self.step = int(step)
        self.value = float(value)
        self.threshold = None if threshold is None else float(threshold)

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"AnomalyEvent({self.kind}, step={self.step}, "
                f"value={self.value!r})")


class NumericsAnomalyDetector:
    """NaN/Inf and spike detection over scalar training signals.

    Spikes are judged against the rolling MEDIAN of the last ``window``
    healthy values (median, not mean: one earlier spike must not drag the
    baseline up and mask the next one). Detection starts after
    ``min_history`` healthy observations; NaN/Inf fire immediately and are
    never added to the baseline."""

    def __init__(self, window=64, spike_factor=10.0, min_history=8):
        self.spike_factor = float(spike_factor)
        self.min_history = int(min_history)
        self._hist = {"loss": deque(maxlen=int(window)),
                      "grad_norm": deque(maxlen=int(window))}

    def _check_one(self, name, step, value):
        v = float(value)
        if math.isnan(v):
            return AnomalyEvent(f"nan_{name}", step, v)
        if math.isinf(v):
            return AnomalyEvent(f"inf_{name}", step, v)
        hist = self._hist[name]
        event = None
        if len(hist) >= self.min_history:
            base = statistics.median(hist)
            threshold = self.spike_factor * max(abs(base), 1e-12)
            if abs(v) > threshold:
                event = AnomalyEvent(f"{name}_spike", step, v, threshold)
        if event is None:
            hist.append(v)  # only healthy values extend the baseline
        return event

    def check(self, step, loss=None, grad_norm=None):
        """Returns the (possibly empty) list of AnomalyEvents for this step."""
        events = []
        for name, value in (("loss", loss), ("grad_norm", grad_norm)):
            if value is None:
                continue
            ev = self._check_one(name, step, value)
            if ev is not None:
                events.append(ev)
        return events


class StepMonitor:
    """Live telemetry attached to a ``TrainStep``.

    Usage (bare loop)::

        mon = StepMonitor(samples_per_step=B, tokens_per_step=B * S)
        mon.bind(step)                       # step = TrainStep(...)
        for x, y in loader:
            loss = step(x, labels=y)         # spans + metrics emitted here
        print(mon.last_fields)               # {'step': ..., 'ips': ..., 'mfu': ...}

    ``Model.fit`` users bind it through ``hapi.callbacks.MonitorCallback``.
    ``enabled=False`` turns every hook into an early return (the
    ``train_observability_overhead`` bench leg measures the on-vs-off delta;
    gate ≤ 3%).  Pass ``log_writer=LogWriter(...)`` to stream the scalar
    series (``train/loss``, ``train/step_time_s``, ``train/ips``,
    ``train/mfu``) to the VisualDL-role log.
    """

    def __init__(self, registry=None, tracer=None, *, samples_per_step=None,
                 tokens_per_step=None, peak_flops="auto", flops_per_step=None,
                 detector=None, log_writer=None, log_freq=1, loss_every=1,
                 lint=True, enabled=True, clock=time.perf_counter):
        self.enabled = bool(enabled)
        # graph lint at first compile: one extra abstract trace per bound
        # step (paddle_tpu.analysis), findings counted in
        # paddle_analysis_findings_total{rule,severity}. lint=False opts out.
        self.lint = bool(lint)
        self.lint_report = None
        self._lint_pending = self.lint
        self.registry = registry if registry is not None else MetricsRegistry()
        self.tracer = tracer if tracer is not None else Tracer(clock=clock)
        self.detector = (detector if detector is not None
                         else NumericsAnomalyDetector())
        self.log_writer = log_writer
        self.log_freq = max(1, int(log_freq))
        self.loss_every = max(0, int(loss_every))  # 0: never fetch the loss
        self.samples_per_step = samples_per_step
        self.tokens_per_step = tokens_per_step
        self._peak_flops = peak_flops
        self._flops_per_step = flops_per_step
        self._clock = clock
        self._trace_id = new_trace_id()
        self._seen_avals: set = set()
        self._step_n = 0
        self._recompiles = 0
        self._launch_us = None
        self._last_step_end_us = None
        self.last_fields: dict = {}
        self.anomalies: deque = deque(maxlen=256)
        self.hbm_stats: dict = {}

        reg = self.registry
        self._m_steps = reg.counter(
            "paddle_train_steps_total", "optimizer steps executed")
        self._m_step_seconds = reg.histogram(
            "paddle_train_step_seconds",
            "per-step wall time (launch to loss readback)",
            buckets=TRAIN_STEP_BUCKETS)
        self._m_ips = reg.gauge(
            "paddle_train_samples_per_sec", "samples/sec of the last step")
        self._m_tps = reg.gauge(
            "paddle_train_tokens_per_sec", "tokens/sec of the last step")
        self._m_mfu = reg.gauge(
            "paddle_train_mfu",
            "live MFU: cost_analysis FLOPs / wall / chip bf16 peak")
        self._m_loss = reg.gauge("paddle_train_loss", "last fetched loss")
        self._m_flops = reg.gauge(
            "paddle_train_model_flops_per_step",
            "compiled-step FLOPs per cost_analysis")
        self._m_hbm = reg.gauge(
            "paddle_train_hbm_bytes",
            "compiled-step HBM footprint per memory_analysis",
            labels=("kind",))
        self._m_recompiles = reg.counter(
            "paddle_train_recompiles_total",
            "XLA recompiles after the first (new argument shapes)",
            labels=("reason",))
        self._m_anomalies = reg.counter(
            "paddle_train_anomalies_total",
            "numerics anomalies (NaN/Inf/spike on loss and grad norm)",
            labels=("kind",))
        self._m_findings = reg.counter(
            "paddle_analysis_findings_total",
            "graph-lint findings on the bound step at first compile",
            labels=("rule", "severity"))
        # ---- preemption-tolerance accounting (framework.checkpoint feeds
        # the phase timings; steps feed the useful-time numerator)
        self._m_goodput = reg.gauge(
            "paddle_train_goodput",
            "useful-step time / wall time since first activity "
            "(wall includes checkpoint snapshots and restore)")
        self._m_ckpt_seconds = reg.histogram(
            "paddle_train_checkpoint_seconds",
            "checkpoint phase wall (snapshot blocks the loop; serialize/"
            "commit overlap compute; restore is resume cost)",
            labels=("phase",), buckets=TRAIN_STEP_BUCKETS)
        self._m_ckpts = reg.counter(
            "paddle_train_checkpoints_total",
            "checkpoints by terminal result",
            labels=("result",))
        self._useful_s = 0.0
        self._ckpt_s = 0.0
        self._wall_t0_us = None

    # ------------------------------------------------------------------ time
    def now_us(self) -> float:
        return self._clock() * 1e6

    # -------------------------------------------------------------- binding
    def bind(self, step):
        """Attach to a ``jit/train.py:TrainStep``: the step's hooks start
        reporting here. An AOT-primed executable is introspected immediately
        (FLOPs + HBM gauges) and its avals seed the recompile sentinel."""
        step._monitor = self
        pending = getattr(step, "_pending_monitor_counters", None)
        if pending is not None:
            # the step was checkpoint-restored before any monitor was bound:
            # adopt its counters so the metric series stays continuous
            self.import_counters(pending)
            step._pending_monitor_counters = None
        if getattr(step, "_compiled_avals", None) is not None:
            # the AOT program was compiled before we were watching: seed the
            # sentinel with an event but never count it as a recompile
            self._sentinel(step._compiled_avals, "aot_prime", self.now_us(),
                           count=False)
        if getattr(step, "_compiled", None) is not None:
            self.observe_compiled(step._compiled)
        return self

    def detach(self, step):
        if getattr(step, "_monitor", None) is self:
            step._monitor = None

    # ------------------------------------------------- compiled introspection
    def observe_compiled(self, compiled):
        """Pull cost/memory analysis off a jax compiled executable into the
        flops + HBM gauges (argument/output/temp/generated-code bytes)."""
        if not self.enabled:
            return
        flops = cost_flops(compiled)
        if flops > 0:
            self._flops_per_step = flops
            self._m_flops.set(flops)
        mem = memory_stats(compiled)
        if mem:
            self.hbm_stats = mem
            for kind in ("argument", "output", "temp", "generated_code",
                         "peak"):
                self._m_hbm.labels(kind).set(mem.get(f"{kind}_bytes", 0))

    @property
    def flops_per_step(self):
        return self._flops_per_step

    @property
    def hbm_peak_bytes(self):
        return self.hbm_stats.get("peak_bytes", 0)

    @property
    def recompiles(self) -> int:
        """Compiles triggered by a NEW argument fingerprint after the first
        program was built (the silent-retrace bug class)."""
        return self._recompiles

    def set_throughput_units(self, samples_per_step=None, tokens_per_step=None):
        if samples_per_step is not None:
            self.samples_per_step = samples_per_step
        if tokens_per_step is not None:
            self.tokens_per_step = tokens_per_step

    def peak_flops(self):
        if self._peak_flops == "auto":
            try:
                import jax

                self._peak_flops = device_peak_flops(jax.devices()[0])
            except Exception:
                self._peak_flops = None
        return self._peak_flops

    # ------------------------------------------------------- TrainStep hooks
    def step_begin(self):
        """Hook 1/3 (TrainStep.__call__ entry). Returns the t0 token."""
        if not self.enabled:
            return None
        now = self.now_us()
        if self._wall_t0_us is None:
            self._wall_t0_us = now
        return now

    def _sentinel(self, key, reason_if_new, when_us, count=True):
        """New fingerprint == XLA built a new program: count (except the
        very first compile) and emit a point trace event either way."""
        if key in self._seen_avals:
            return
        first = not self._seen_avals
        self._seen_avals.add(key)
        reason = "first" if first else reason_if_new
        if count and not first:
            self._recompiles += 1
            self._m_recompiles.labels(reason).inc()
        self.tracer.record("compile", when_us, when_us, self._trace_id,
                           tags={"reason": reason, "step": self._step_n + 1,
                                 "shapes": repr(key[-1])[:200]})

    def before_launch(self, step, args, kwargs, aot_hit, t0):
        """Hook 2/3 (inputs staged, about to launch): closes the ``h2d``
        span and runs the recompilation sentinel over the argument avals."""
        if not self.enabled or t0 is None:
            return
        now = self.now_us()
        self._launch_us = now
        self.tracer.record("h2d", t0, now, self._trace_id,
                           tags={"step": self._step_n + 1})
        reason = ("aot_fallback" if (step._compiled is not None
                                     and not aot_hit) else "new_shape")
        self._sentinel(step._arg_avals(args, kwargs), reason, now)
        if self._lint_pending:
            self._run_lint(step, args, kwargs)

    def before_scan_launch(self, step, n_steps, flags, args, kwargs, t0):
        """run_steps twin of before_launch: the fingerprint also covers the
        scan length and the stacked/const split (each combination is its own
        compiled program in the scan cache)."""
        if not self.enabled or t0 is None:
            return
        now = self.now_us()
        self._launch_us = now
        self.tracer.record("h2d", t0, now, self._trace_id,
                           tags={"step": self._step_n + 1,
                                 "n_steps": n_steps})
        self._sentinel(("scan", n_steps, flags,
                        step._arg_avals(args, kwargs)), "new_shape", now)
        if self._lint_pending:
            self._run_lint(step, args, kwargs)

    # ---------------------------------------------------------- graph lint
    def _run_lint(self, step, args, kwargs):
        """Lint the bound step ONCE at first compile (the step is about to
        trace anyway — this is when a donation-miss or dtype-upcast finding
        is cheapest to surface). One extra abstract trace; findings become
        ``paddle_analysis_findings_total{rule,severity}`` and a point trace
        event. Never raises: telemetry must not take down the loop."""
        self._lint_pending = False
        now = self.now_us()
        try:
            from .. import analysis

            report = analysis.analyze_train_step(step, *args, **kwargs)
            self.lint_report = report
            for f in report.findings:
                self._m_findings.labels(f.rule, f.severity).inc()
            self.tracer.record(
                "graph_lint", now, self.now_us(), self._trace_id,
                tags={"findings": len(report.findings),
                      "high": len(report.high()),
                      "suppressed": len(report.suppressed),
                      "by_rule": repr(report.by_rule())[:200]})
        except Exception as e:  # pragma: no cover - defensive
            self.tracer.record("graph_lint", now, self.now_us(),
                               self._trace_id,
                               tags={"error": repr(e)[:200]})

    def step_end(self, step, loss_val, t0, n_steps=1):
        """Hook 3/3 (state written back): closes the ``step`` span, updates
        throughput/MFU gauges, fetches the loss (every ``loss_every`` steps)
        and feeds the anomaly detector."""
        if not self.enabled or t0 is None:
            return
        # fetch the loss BEFORE stamping the end time: the fetch is the
        # honest step boundary (it blocks on the device), and the step wall /
        # goodput useful-time must include the compute it waits for — with a
        # periodic cadence (loss_every=K) the fetch step absorbs the queued
        # compute of the K-1 async-dispatched steps before it, so the SUM of
        # step walls stays right even when each individual one is not
        loss_f = None
        if self.loss_every and (self._step_n + n_steps) % self.loss_every \
                == 0 and loss_val is not None:
            try:
                loss_f = float(loss_val)
            except Exception:
                loss_f = None
        end = self.now_us()
        launch = self._launch_us if self._launch_us is not None else t0
        self._launch_us = None
        self._step_n += n_steps
        self._last_step_end_us = end
        name = "step" if n_steps == 1 else "run_steps"
        self.tracer.record(name, launch, end, self._trace_id,
                           tags={"step": self._step_n, "n_steps": n_steps})
        dt_s = max((end - t0) / 1e6, 1e-12) / n_steps
        self._m_steps.inc(n_steps)
        self._m_step_seconds.observe(dt_s)
        self._useful_s += (end - t0) / 1e6
        fields = {"step": self._step_n, "step_time_s": dt_s}
        gp = self._goodput_at(end)
        if gp is not None:
            fields["goodput"] = gp
            self._m_goodput.set(gp)
        if self.samples_per_step:
            fields["ips"] = self.samples_per_step / dt_s
            self._m_ips.set(fields["ips"])
        if self.tokens_per_step:
            fields["tokens_per_sec"] = self.tokens_per_step / dt_s
            self._m_tps.set(fields["tokens_per_sec"])
        peak = self.peak_flops()
        if self._flops_per_step and peak:
            fields["mfu"] = self._flops_per_step / dt_s / peak
            self._m_mfu.set(fields["mfu"])
        if loss_f is not None:
            fields["loss"] = loss_f
            self._m_loss.set(loss_f)
            self.observe_scalars(self._step_n, loss=loss_f)
        self.last_fields = fields
        if self.log_writer is not None and self._step_n % self.log_freq == 0:
            for tag in ("loss", "step_time_s", "ips", "tokens_per_sec",
                        "mfu"):
                if tag in fields:
                    self.log_writer.add_scalar(f"train/{tag}", fields[tag],
                                               step=self._step_n)

    # ------------------------------------------- checkpointing & goodput
    def _goodput_at(self, now_us):
        """useful-step seconds / wall seconds since the first activity this
        monitor saw (a step, a checkpoint phase, or a restore). Wall time
        includes checkpoint snapshots, restore, data waits — everything a
        preemption-tolerant run pays that is not a training step."""
        if self._wall_t0_us is None:
            return None
        wall = (now_us - self._wall_t0_us) / 1e6
        if wall <= 0:
            return None
        return min(1.0, self._useful_s / wall)

    @property
    def goodput(self):
        return self._goodput_at(self.now_us())

    @property
    def useful_step_seconds(self):
        return self._useful_s

    @property
    def checkpoint_seconds(self):
        """Total seconds spent in checkpoint phases (all phases, incl.
        restore) reported to this monitor."""
        return self._ckpt_s

    def checkpoint_phase(self, phase, seconds):
        """``framework.checkpoint.CheckpointManager`` hook: one finished
        phase (``snapshot`` | ``serialize`` | ``commit`` | ``restore``).
        Lands in the phase histogram, a span on the step timeline, and the
        goodput wall window (a restore that happened before the first step
        backdates the window so resume cost counts against goodput)."""
        if not self.enabled:
            return
        seconds = max(0.0, float(seconds))
        now = self.now_us()
        start = now - seconds * 1e6
        if self._wall_t0_us is None or start < self._wall_t0_us:
            self._wall_t0_us = start
        self._ckpt_s += seconds
        self._m_ckpt_seconds.labels(phase).observe(seconds)
        self.tracer.record(f"ckpt_{phase}", start, now, self._trace_id,
                           tags={"step": self._step_n})
        gp = self._goodput_at(now)
        if gp is not None:
            self._m_goodput.set(gp)

    def checkpoint_result(self, ok=True, step=None):
        """One checkpoint reached a terminal result (manifest committed, or
        the async writer failed)."""
        if not self.enabled:
            return
        self._m_ckpts.labels("committed" if ok else "failed").inc()

    def export_counters(self):
        """Counters that survive a preemption inside a checkpoint (the
        ``TrainStep.export_state`` meta): the step number keeps the metric
        series continuous across resume. Time windows (goodput) restart per
        process — resume cost is charged to the NEW process's window."""
        return {"step_n": int(self._step_n)}

    def import_counters(self, counters):
        self._step_n = int(counters.get("step_n", self._step_n))

    # ---------------------------------------------------------- numerics
    def observe_scalars(self, step=None, loss=None, grad_norm=None):
        """Feed scalar signals to the anomaly detector (the step hook feeds
        the loss automatically; callers with a host-side grad norm — e.g. a
        clip-by-global-norm readback — feed it here)."""
        if not self.enabled:
            return []
        events = self.detector.check(
            self._step_n if step is None else step, loss=loss,
            grad_norm=grad_norm)
        for ev in events:
            self.anomalies.append(ev)
            self._m_anomalies.labels(ev.kind).inc()
            t = self.now_us()
            self.tracer.record("anomaly", t, t, self._trace_id,
                               tags={"kind": ev.kind, "step": ev.step,
                                     "value": ev.value})
        return events

    # -------------------------------------------------------------- phases
    @contextmanager
    def phase(self, name, **tags):
        """Span a host-side phase (``data_wait``, ``callbacks``) onto the
        same step timeline::

            with mon.phase("data_wait"):
                batch = next(it)
        """
        if not self.enabled:
            yield self
            return
        t0 = self.now_us()
        try:
            yield self
        finally:
            self.tracer.record(name, t0, self.now_us(), self._trace_id,
                               tags=dict(tags, step=self._step_n + 1))

    def record_phase(self, name, start_us, end_us, **tags):
        """Explicit-timestamp phase (cross-callback intervals)."""
        if not self.enabled:
            return
        self.tracer.record(name, start_us, end_us, self._trace_id,
                           tags=dict(tags, step=self._step_n + 1))

    @property
    def last_step_end_us(self):
        return self._last_step_end_us

    # -------------------------------------------------------------- export
    def render(self) -> str:
        """This monitor's registry as a Prometheus text exposition (merge
        with serving registries via ``render_prometheus``)."""
        return self.registry.render()
