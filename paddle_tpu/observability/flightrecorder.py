"""Flight recorder: a bounded ring of per-tick scheduler snapshots.

The continuous scheduler calls ``record()`` once per tick (after the decode
phase) with a plain-dict snapshot of the slot map (tenant/adapter/phase per
slot), batch widths, KV-cache block accounting, paused/pending depths, and
the QoS ledger's fair-share ratios. The ring is the postmortem the scrape
can't be: when an SLO burn-rate alert fires, when an operator hits
``/debug/ticks``, or when a chaos test fails, ``dump()`` serializes the
newest N ticks so the breach window's actual slot state ships with the
failure instead of dying with the process.

Capture cost is a handful of dict builds per tick under the slot lock —
the ``slo_observability`` bench leg gates recorder+attribution overhead at
<=5% on the serving pressure workload. The recorder itself takes no locks
of the scheduler's; thread safety of its own ring is a single mutex.

Module-level ``live_recorders()`` / ``dump_all()`` expose every live
recorder through a WeakSet so the chaos conftest fixture can dump rings it
never got a handle to (recorders die with their schedulers; the registry
holds no references).
"""
from __future__ import annotations

import collections
import json
import threading
import time
import weakref

__all__ = ["FlightRecorder", "live_recorders", "dump_all"]

_LIVE: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()


def live_recorders():
    """All currently-live recorders (weakly tracked, creation order lost)."""
    return list(_LIVE)


def dump_all(last=None):
    """Dump every live recorder, keyed by name (chaos-fixture entrypoint)."""
    return {rec.name: rec.dump(last=last) for rec in live_recorders()}


class FlightRecorder:
    """Bounded ring of per-tick snapshots with alert/dump plumbing.

    capacity  max retained ticks (oldest evicted; overhead and memory are
              O(capacity), dump size is the operator's to bound via `last`).
    clock     injectable monotonic clock, seconds (chaos skew compatible).
    name      dump-key / metric disambiguator; auto-numbered if omitted.
    """

    _seq = 0
    _seq_lock = threading.Lock()

    def __init__(self, capacity=512, clock=time.monotonic, name=None):
        if int(capacity) <= 0:
            raise ValueError("flight-recorder capacity must be positive")
        if name is None:
            with FlightRecorder._seq_lock:
                FlightRecorder._seq += 1
                name = f"flightrec-{FlightRecorder._seq}"
        self.name = str(name)
        self._capacity = int(capacity)
        self._clock = clock
        self._ring: collections.deque = collections.deque(
            maxlen=self._capacity)
        self._lock = threading.Lock()
        self._recorded = 0          # lifetime ticks, for dropped accounting
        self._alerts: collections.deque = collections.deque(maxlen=32)
        _LIVE.add(self)

    # --------------------------------------------------------------- capture
    def record(self, snapshot: dict):
        """Append one tick snapshot (a plain JSON-serializable dict). The
        recorder stamps ``t`` and a monotonically increasing ``tick``."""
        with self._lock:
            self._recorded += 1
            entry = {"tick": self._recorded, "t": round(self._clock(), 6)}
            entry.update(snapshot)
            self._ring.append(entry)

    def mark_alert(self, slo, **context):
        """Note an SLO alert edge (kept alongside the ring so a dump shows
        *when* the page fired relative to the ticks it contains)."""
        with self._lock:
            self._alerts.append({
                "t": round(self._clock(), 6), "slo": str(slo),
                "at_tick": self._recorded, **context,
            })

    # ------------------------------------------------------------ inspection
    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def occupancy(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def dropped(self) -> int:
        """Ticks evicted from the ring (lifetime recorded - retained)."""
        with self._lock:
            return max(0, self._recorded - len(self._ring))

    def dump(self, last=None) -> dict:
        """JSON-ready artifact: newest-last ticks (optionally only the last
        `last`), alert marks, and ring accounting."""
        with self._lock:
            ticks = list(self._ring)
            alerts = list(self._alerts)
            recorded = self._recorded
        dropped = max(0, recorded - len(ticks))
        if last is not None:
            ticks = ticks[-int(last):]
        return {
            "name": self.name,
            "capacity": self._capacity,
            "recorded": recorded,
            "dropped": dropped,
            "occupancy": len(ticks),
            "alerts": alerts,
            "ticks": ticks,
        }

    def dump_json(self, last=None) -> str:
        return json.dumps(self.dump(last=last), sort_keys=True)

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._alerts.clear()
