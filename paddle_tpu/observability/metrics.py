"""Typed metrics registry with Prometheus text exposition.

Reference role: the scrape surface of production serving stacks
(prometheus_client's Counter/Gauge/Histogram model, exposition text format
0.0.4) without taking a dependency — the serving runtime needs ~200 lines of
it: typed families, label children, callback gauges for pool state, and a
validated text renderer the exposition-lint test can hold to the format.

Contracts:

* a metric NAME owns one type forever — re-registering with a different
  type, help string or label set raises (get-or-create otherwise, so the
  serving layer can bind families idempotently across restarts);
* counters are monotonic (negative ``inc`` raises);
* gauges may read through a callback (``set_function``) so pool state is
  sampled at scrape time instead of maintained by hand;
* histograms render cumulative ``_bucket{le=...}`` series plus ``_sum`` /
  ``_count`` (le values formatted so a Prometheus parser round-trips them);
* ``render_prometheus(*registries)`` merges families across registries,
  emitting each ``# HELP``/``# TYPE`` block exactly once and raising on
  duplicate series — the /metrics endpoint serves several components
  (batcher, generator, KV pool, HTTP layer) as ONE valid exposition.
"""
from __future__ import annotations

import math
import re
import threading

__all__ = ["MetricsRegistry", "render_prometheus", "DEFAULT_LATENCY_BUCKETS"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# latency-in-seconds buckets spanning admission-check (~us) to decode (~s)
DEFAULT_LATENCY_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                           0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(v: float) -> str:
    """Float -> exposition value: integers render bare (counter idiom).
    NaN is a legal exposition value (a NaN loss gauge must render, not
    crash the scrape)."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if math.isnan(f):
        return "NaN"
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class _Child:
    """One labeled series of a family."""

    __slots__ = ("_family", "_lock", "_value", "_fn", "_buckets", "_counts",
                 "_sum")

    def __init__(self, family):
        self._family = family
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn = None
        if family.type == "histogram":
            self._buckets = family.buckets
            self._counts = [0] * (len(family.buckets) + 1)  # +Inf last
            self._sum = 0.0

    # ---------------------------------------------------------------- counter
    def inc(self, n=1):
        if self._family.type not in ("counter", "gauge"):
            raise TypeError(f"inc() on a {self._family.type}")
        if self._family.type == "counter" and n < 0:
            raise ValueError("counters are monotonic; inc() must be >= 0")
        with self._lock:
            self._value += n

    # ------------------------------------------------------------------ gauge
    def dec(self, n=1):
        if self._family.type != "gauge":
            raise TypeError(f"dec() on a {self._family.type}")
        with self._lock:
            self._value -= n

    def set(self, v):
        if self._family.type != "gauge":
            raise TypeError(f"set() on a {self._family.type}")
        with self._lock:
            self._value = float(v)

    def set_function(self, fn):
        """Read this series through `fn()` at scrape time (pool state)."""
        if self._family.type not in ("gauge", "counter"):
            raise TypeError(f"set_function() on a {self._family.type}")
        with self._lock:
            self._fn = fn

    # -------------------------------------------------------------- histogram
    def observe(self, v):
        if self._family.type != "histogram":
            raise TypeError(f"observe() on a {self._family.type}")
        v = float(v)
        with self._lock:
            self._sum += v
            for i, b in enumerate(self._buckets):
                if v <= b:
                    self._counts[i] += 1
                    break
            else:
                self._counts[-1] += 1

    # ------------------------------------------------------------------ value
    @property
    def value(self):
        with self._lock:
            return float(self._fn()) if self._fn is not None else self._value

    def histogram_state(self):
        with self._lock:
            return list(self._counts), self._sum


class _Family:
    def __init__(self, name, help, type, labelnames, buckets=None):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r}")
        if type == "histogram":
            if "le" in labelnames:
                raise ValueError("'le' is reserved on histograms")
            buckets = tuple(sorted(float(b) for b in (buckets or
                                                      DEFAULT_LATENCY_BUCKETS)))
            if not buckets:
                raise ValueError("histogram needs at least one bucket")
        self.name = name
        self.help = help
        self.type = type
        self.labelnames = tuple(labelnames)
        self.buckets = buckets
        self._lock = threading.Lock()
        self._children: dict[tuple, _Child] = {}

    def labels(self, *values, **kv) -> _Child:
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            try:
                values = tuple(kv[ln] for ln in self.labelnames)
            except KeyError as e:
                raise ValueError(f"missing label {e} for {self.name}") from e
            if len(kv) != len(self.labelnames):
                raise ValueError(f"unexpected labels for {self.name}: "
                                 f"{sorted(set(kv) - set(self.labelnames))}")
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} takes {len(self.labelnames)} label values, "
                f"got {len(values)}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = _Child(self)
            return child

    # the no-labels family IS its only child
    def inc(self, n=1):
        self.labels().inc(n)

    def dec(self, n=1):
        self.labels().dec(n)

    def set(self, v):
        self.labels().set(v)

    def set_function(self, fn):
        self.labels().set_function(fn)

    def observe(self, v):
        self.labels().observe(v)

    @property
    def value(self):
        return self.labels().value

    def children(self):
        with self._lock:
            return list(self._children.items())


class MetricsRegistry:
    """Name -> family map with get-or-create typed registration."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _get_or_create(self, name, help, type, labels, buckets=None):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if (fam.type != type or fam.labelnames != tuple(labels)
                        or (help and fam.help and fam.help != help)):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.type}{fam.labelnames} — cannot re-register as "
                        f"{type}{tuple(labels)}")
                return fam
            fam = _Family(name, help, type, labels, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name, help="", labels=()) -> _Family:
        return self._get_or_create(name, help, "counter", labels)

    def gauge(self, name, help="", labels=()) -> _Family:
        return self._get_or_create(name, help, "gauge", labels)

    def histogram(self, name, help="", labels=(), buckets=None) -> _Family:
        return self._get_or_create(name, help, "histogram", labels, buckets)

    def families(self):
        with self._lock:
            return list(self._families.values())

    def render(self) -> str:
        return render_prometheus(self)


def _series_line(name, labelnames, labelvalues, value, extra=None):
    pairs = [f'{ln}="{_escape_label(lv)}"'
             for ln, lv in zip(labelnames, labelvalues)]
    if extra:
        pairs.append(f'{extra[0]}="{_escape_label(extra[1])}"')
    lbl = "{" + ",".join(pairs) + "}" if pairs else ""
    return f"{name}{lbl} {_fmt(value)}"


def render_prometheus(*registries) -> str:
    """One valid text exposition (format 0.0.4) over several registries.

    Families sharing a name across registries must agree on type/labels (the
    batcher and generator deliberately share families, disambiguated by a
    ``component`` label); a genuinely duplicated series raises instead of
    silently rendering an invalid exposition."""
    merged: dict[str, list] = {}
    order: list[str] = []
    seen_regs = []
    for reg in registries:
        if reg is None or any(reg is r for r in seen_regs):
            continue  # same registry wired to several components: render once
        seen_regs.append(reg)
        for fam in reg.families():
            if fam.name in merged:
                ref = merged[fam.name][0]
                if (ref.type != fam.type
                        or ref.labelnames != fam.labelnames):
                    raise ValueError(
                        f"conflicting definitions of metric {fam.name!r}")
                merged[fam.name].append(fam)
            else:
                merged[fam.name] = [fam]
                order.append(fam.name)

    lines = []
    for name in order:
        fams = merged[name]
        ref = fams[0]
        help_text = next((f.help for f in fams if f.help), "")
        lines.append(f"# HELP {name} {help_text}".rstrip())
        lines.append(f"# TYPE {name} {ref.type}")
        seen_series = set()

        def emit(full_name, labelvalues, value, extra=None):
            key = (full_name, labelvalues, extra[1] if extra else None)
            if key in seen_series:
                raise ValueError(f"duplicate series {full_name}{labelvalues}")
            seen_series.add(key)
            lines.append(_series_line(full_name, ref.labelnames, labelvalues,
                                      value, extra))

        for fam in fams:
            for labelvalues, child in fam.children():
                if ref.type == "histogram":
                    counts, total = child.histogram_state()
                    cum = 0
                    for b, c in zip(ref.buckets, counts):
                        cum += c
                        emit(f"{name}_bucket", labelvalues, cum,
                             extra=("le", _fmt(b)))
                    cum += counts[-1]
                    emit(f"{name}_bucket", labelvalues, cum,
                         extra=("le", "+Inf"))
                    emit(f"{name}_sum", labelvalues, total)
                    emit(f"{name}_count", labelvalues, cum)
                else:
                    emit(name, labelvalues, child.value)
    return "\n".join(lines) + ("\n" if lines else "")
