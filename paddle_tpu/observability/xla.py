"""XLA compiled-program introspection, normalized.

What XLA already knows about a compiled training step is the cheapest
telemetry there is — it costs nothing at step time because it was computed at
compile time. This module is the one place that normalizes the two relevant
surfaces across jax versions and backends:

* ``cost_analysis`` / ``cost_flops`` — the compiled program's own FLOP count
  (jax returns a dict on some versions, a 1-list of dicts on others; some
  backends return nothing).  This is the number bench.py's MFU audit and the
  live ``StepMonitor`` MFU must AGREE on, which is why both now import it
  from here instead of keeping private copies.
* ``memory_stats`` — ``compiled.memory_analysis()`` (XLA's
  ``CompiledMemoryStats``) flattened to plain ints: argument / output / temp /
  generated-code / alias bytes plus a derived ``peak_bytes`` watermark
  (arguments + outputs + temps + generated code − aliased), the HBM number a
  creeping-toward-OOM alert wants.  Backends with no CompiledMemoryStats fall
  back to the static estimator (analysis/hbm.py), tagged ``estimated=True``.
* ``device_peak_flops`` — per-chip dense bf16 peak (public TPU specs), the
  denominator of MFU.  ``None`` off-accelerator so MFU degrades to "absent",
  never to a made-up number.

Everything here is defensive: an introspection surface a backend does not
implement yields ``{}`` / ``0.0`` / ``None``, never an exception — telemetry
must not be able to take down the training loop it watches.
"""
from __future__ import annotations

__all__ = ["cost_analysis", "cost_flops", "memory_stats",
           "device_peak_flops", "PEAK_BF16_FLOPS",
           "device_ici_bandwidth", "ICI_BANDWIDTH_BYTES"]

# Per-chip peak bf16 TFLOP/s (dense), from public TPU specs. The single
# source of truth — bench.py's _chip_peak reads this table.
PEAK_BF16_FLOPS = {
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def device_peak_flops(device) -> float | None:
    """Dense bf16 peak FLOP/s of `device`, or None when unknown (CPU, new
    chip revisions): MFU is reported only when the denominator is real."""
    kind = getattr(device, "device_kind", "") or ""
    for name, peak in PEAK_BF16_FLOPS.items():
        if kind.startswith(name):
            return peak
    return None


# Per-chip aggregate ICI bandwidth in BYTES/s (public TPU specs: v3
# 6x112 Gbps/link ≈ 656 Gbps, v4 2400 Gbps, v5e 1600 Gbps, v5p 4800 Gbps,
# v6e/Trillium 3584 Gbps — bits on the spec sheet, bytes here). The
# bandwidth sibling of PEAK_BF16_FLOPS: the comms lint's comms-over-budget
# rule (analysis/comms.py) divides per-tick wire bytes by this.
ICI_BANDWIDTH_BYTES = {
    "TPU v3": 656e9 / 8,
    "TPU v4": 2400e9 / 8,
    "TPU v5 lite": 1600e9 / 8,
    "TPU v5e": 1600e9 / 8,
    "TPU v5p": 4800e9 / 8,
    "TPU v5": 4800e9 / 8,
    "TPU v6 lite": 3584e9 / 8,
    "TPU v6e": 3584e9 / 8,
}


def device_ici_bandwidth(device) -> float | None:
    """Per-chip ICI bandwidth of `device` in bytes/s, or None when unknown
    (CPU, new chip revisions): the comms budget gate runs only when the
    denominator is real, same contract as device_peak_flops."""
    kind = getattr(device, "device_kind", "") or ""
    for name, bw in ICI_BANDWIDTH_BYTES.items():
        if kind.startswith(name):
            return bw
    return None


def cost_analysis(compiled) -> dict:
    """`compiled.cost_analysis()` as a plain dict ({} when unavailable)."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost) if cost else {}


def cost_flops(compiled) -> float:
    """FLOPs of one execution of `compiled` per its own cost analysis
    (0.0 when the backend does not report them)."""
    try:
        return float(cost_analysis(compiled).get("flops", 0.0))
    except Exception:
        return 0.0


_MEM_FIELDS = {
    "argument": "argument_size_in_bytes",
    "output": "output_size_in_bytes",
    "temp": "temp_size_in_bytes",
    "generated_code": "generated_code_size_in_bytes",
    "alias": "alias_size_in_bytes",
}


def memory_stats(compiled, jaxpr=None) -> dict:
    """`compiled.memory_analysis()` flattened to ints.

    Keys: ``argument_bytes``, ``output_bytes``, ``temp_bytes``,
    ``generated_code_bytes``, ``alias_bytes`` and the derived watermark
    ``peak_bytes`` = argument + output + temp + generated_code − alias
    (aliased donated buffers are counted once).

    Backends with no ``CompiledMemoryStats`` fall back to the static
    estimator (analysis/hbm.py) instead of returning ``{}``: the full
    liveness walk when the caller passes the program's ``jaxpr``, else a
    degraded tier from the executable's aval/donation metadata alone.
    Fallback dicts carry ``estimated=True`` so dashboards can tell a real
    watermark from a model of one — either way,
    ``paddle_train_hbm_bytes{kind}`` stops reading zero on stats-less
    hosts. ``{}`` only when no surface yields anything."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is None:
        return _estimated_memory_stats(compiled, jaxpr)
    out = {}
    for key, attr in _MEM_FIELDS.items():
        try:
            out[f"{key}_bytes"] = int(getattr(ma, attr))
        except Exception:
            out[f"{key}_bytes"] = 0
    out["peak_bytes"] = max(0, out["argument_bytes"] + out["output_bytes"]
                            + out["temp_bytes"] + out["generated_code_bytes"]
                            - out["alias_bytes"])
    return out


def _estimated_memory_stats(compiled, jaxpr) -> dict:
    """The ``estimated=True`` degraded path, in its own frame so the lazy
    analysis import cannot shadow a real-stats failure (telemetry must not
    take down the loop it watches)."""
    try:
        from ..analysis.hbm import estimate_memory_stats

        return estimate_memory_stats(jaxpr, compiled=compiled)
    except Exception:
        return {}
