"""paddle.jit: trace-and-compile. Reference: python/paddle/jit/api.py:197 (to_static),
SOT + AST tracers under python/paddle/jit/{sot,dy2static}.

TPU-native replacement for the whole SOT/AST/PIR pipeline: the op layer already runs on
jax, so `to_static` is jax.jit over the Python function — Python IS the tracer, XLA is
the compiler. Guards/graph-breaks are unnecessary: jit retraces per (structure, shape,
dtype) signature automatically; data-dependent Python control flow raises a clear
TracerBoolConversionError instead of silently graph-breaking.
"""
from __future__ import annotations

import functools
import os
import pickle

import jax
import numpy as np

from ..nn.layer import Layer
from ..tensor import Tensor

__all__ = ["to_static", "not_to_static", "ignore_module", "save", "load",
           "enable_to_static", "TrainStep"]


def __getattr__(name):
    if name == "TrainStep":
        from .train import TrainStep

        return TrainStep
    raise AttributeError(name)

_to_static_enabled = True


def enable_to_static(flag: bool):
    global _to_static_enabled
    _to_static_enabled = flag


def not_to_static(fn=None):
    if fn is None:
        return lambda f: f
    fn._jit_skip = True
    return fn


def ignore_module(modules):
    return None


class StaticFunction:
    """Compiled callable. For Layers / bound Layer methods, parameters and buffers are
    threaded through the jit boundary as inputs so in-place updates (optimizer steps,
    batch-norm stats) are observed — the reference achieves the same via parameter
    scope capture in its partial programs (python/paddle/jit/dy2static/
    pir_partial_program.py)."""

    def __init__(self, function, input_spec=None, build_strategy=None, backend=None,
                 full_graph=True):
        self._raw_fn = function
        self._layer = None
        fn = function
        if isinstance(function, Layer):
            self._layer = function
            fn = type(function).forward
        elif hasattr(function, "__self__") and isinstance(function.__self__, Layer):
            self._layer = function.__self__
            fn = function.__func__
        self._fn = fn
        self._input_spec = input_spec
        self._cache = {}
        functools.update_wrapper(self, fn)

    @property
    def layer(self):
        return self._layer

    def _jitted(self):
        if "jit" in self._cache:
            return self._cache["jit"]
        layer = self._layer
        fn = self._fn

        if layer is not None:
            def run(state, training, args, kwargs):
                prev = layer.training
                for l in layer.sublayers(include_self=True):
                    l.training = training
                try:
                    return layer.functional_call(state, *args, **kwargs) if fn is type(
                        layer).forward else _call_method(layer, fn, state, args, kwargs)
                finally:
                    for l in layer.sublayers(include_self=True):
                        l.training = prev

            jitted = jax.jit(run, static_argnums=(1,))
        else:
            def run(args, kwargs):
                return fn(*args, **kwargs)

            jitted = jax.jit(run)
        self._cache["jit"] = jitted
        return jitted

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            if self._layer is not None and self._fn is type(self._layer).forward:
                return self._layer(*args, **kwargs)
            return self._raw_fn(*args, **kwargs)
        jitted = self._jitted()
        if self._layer is not None:
            state = self._layer.raw_state()
            out = jitted(state, self._layer.training, args, kwargs)
            return out
        return jitted(args, kwargs)

    # reference API surface
    def concrete_program_specify_input_spec(self, *a, **k):
        return None

    @property
    def code(self):
        import inspect

        return inspect.getsource(self._fn)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              **kwargs):
    """Reference: python/paddle/jit/api.py:197. backend arg accepted for compat (CINN →
    XLA is always on)."""

    def decorate(fn):
        return StaticFunction(fn, input_spec, build_strategy, backend)

    if function is None:
        return decorate
    return decorate(function)


def _call_method(layer, fn, state, args, kwargs):
    sd = layer.state_dict()
    saved = {k: t._value for k, t in sd.items()}
    try:
        for k, v in state.items():
            if k in sd:
                sd[k]._value = v
        return fn(layer, *args, **kwargs)
    finally:
        for k, t in sd.items():
            t._value = saved[k]


class TranslatedLayer(Layer):
    """Loaded inference layer (reference: translated_layer.py)."""

    def __init__(self, state, meta, forward_fn=None):
        super().__init__()
        self._state = state
        self._meta = meta
        self._forward_fn = forward_fn

    def forward(self, *args):
        raise NotImplementedError(
            "TranslatedLayer from paddle_tpu.jit.load holds weights only; rebuild the "
            "model class and call set_state_dict — serialized program replay lands with "
            "the inference runtime."
        )


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save: persist weights + structure metadata. Weights as npz (portable,
    no pickle trust issues for arrays) + a meta pickle for structure."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if isinstance(layer, StaticFunction):
        layer = layer.layer
    state = {k: np.asarray(v._value) for k, v in layer.state_dict().items()}
    np.savez(path + ".pdiparams.npz", **state)
    meta = {
        "class_name": type(layer).__name__,
        "state_keys": list(state.keys()),
        "input_spec": None,
    }
    with open(path + ".pdmodel.meta", "wb") as f:
        pickle.dump(meta, f)


def load(path, **configs):
    with open(path + ".pdmodel.meta", "rb") as f:
        meta = pickle.load(f)
    data = np.load(path + ".pdiparams.npz")
    state = {k: Tensor(jax_asarray(data[k])) for k in data.files}
    return TranslatedLayer(state, meta)


def jax_asarray(a):
    import jax.numpy as jnp

    return jnp.asarray(a)
