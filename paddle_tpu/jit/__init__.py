"""paddle.jit: trace-and-compile. Reference: python/paddle/jit/api.py:197 (to_static),
SOT + AST tracers under python/paddle/jit/{sot,dy2static}.

TPU-native replacement for the whole SOT/AST/PIR pipeline: the op layer already runs on
jax, so `to_static` is jax.jit over the Python function — Python IS the tracer, XLA is
the compiler. Guards/graph-breaks are unnecessary: jit retraces per (structure, shape,
dtype) signature automatically; data-dependent Python control flow raises a clear
TracerBoolConversionError instead of silently graph-breaking.
"""
from __future__ import annotations

import functools
import os
import pickle

import jax
import numpy as np

from ..nn.layer import Layer
from ..tensor import Tensor

__all__ = ["to_static", "not_to_static", "ignore_module", "save", "load",
           "enable_to_static", "TrainStep"]


def __getattr__(name):
    if name == "TrainStep":
        from .train import TrainStep

        return TrainStep
    raise AttributeError(name)

_to_static_enabled = True


def enable_to_static(flag: bool):
    global _to_static_enabled
    _to_static_enabled = flag


def not_to_static(fn=None):
    if fn is None:
        return lambda f: f
    fn._jit_skip = True
    return fn


def ignore_module(modules):
    return None


class StaticFunction:
    """Compiled callable. For Layers / bound Layer methods, parameters and buffers are
    threaded through the jit boundary as inputs so in-place updates (optimizer steps,
    batch-norm stats) are observed — the reference achieves the same via parameter
    scope capture in its partial programs (python/paddle/jit/dy2static/
    pir_partial_program.py)."""

    def __init__(self, function, input_spec=None, build_strategy=None, backend=None,
                 full_graph=True):
        self._raw_fn = function
        self._layer = None
        fn = function
        if isinstance(function, Layer):
            self._layer = function
            fn = type(function).forward
        elif hasattr(function, "__self__") and isinstance(function.__self__, Layer):
            self._layer = function.__self__
            fn = function.__func__
        self._fn = fn
        self._input_spec = input_spec
        self._cache = {}
        functools.update_wrapper(self, fn)

    @property
    def layer(self):
        return self._layer

    def _jitted(self):
        if "jit" in self._cache:
            return self._cache["jit"]
        layer = self._layer
        fn = self._fn

        if layer is not None:
            def run(state, training, args, kwargs):
                prev = layer.training
                for l in layer.sublayers(include_self=True):
                    l.training = training
                try:
                    return layer.functional_call(state, *args, **kwargs) if fn is type(
                        layer).forward else _call_method(layer, fn, state, args, kwargs)
                finally:
                    for l in layer.sublayers(include_self=True):
                        l.training = prev

            jitted = jax.jit(run, static_argnums=(1,))
        else:
            def run(args, kwargs):
                return fn(*args, **kwargs)

            jitted = jax.jit(run)
        self._cache["jit"] = jitted
        return jitted

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            if self._layer is not None and self._fn is type(self._layer).forward:
                return self._layer(*args, **kwargs)
            return self._raw_fn(*args, **kwargs)
        jitted = self._jitted()
        if self._layer is not None:
            state = self._layer.raw_state()
            out = jitted(state, self._layer.training, args, kwargs)
            return out
        return jitted(args, kwargs)

    # reference API surface
    def concrete_program_specify_input_spec(self, *a, **k):
        return None

    @property
    def code(self):
        import inspect

        return inspect.getsource(self._fn)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              **kwargs):
    """Reference: python/paddle/jit/api.py:197. backend arg accepted for compat (CINN →
    XLA is always on)."""

    def decorate(fn):
        return StaticFunction(fn, input_spec, build_strategy, backend)

    if function is None:
        return decorate
    return decorate(function)


def _call_method(layer, fn, state, args, kwargs):
    sd = layer.state_dict()
    saved = {k: t._value for k, t in sd.items()}
    try:
        for k, v in state.items():
            if k in sd:
                sd[k]._value = v
        return fn(layer, *args, **kwargs)
    finally:
        for k, t in sd.items():
            t._value = saved[k]


class TranslatedLayer(Layer):
    """Loaded inference layer replaying a serialized StableHLO program.

    Reference: python/paddle/jit/translated_layer.py (load + execute without
    the original model class; the C++ twin is paddle/fluid/jit/layer.h).
    TPU-native: the program is a ``jax.export`` blob — deserialize once,
    ``call(state, *inputs)`` per forward; XLA compiles per concrete shape
    (symbolic batch dims replay at any batch size).
    """

    def __init__(self, state, meta, exported=None):
        super().__init__()
        self._state = state
        self._meta = meta
        self._exported = exported

    def forward(self, *args):
        if self._exported is None:
            raise RuntimeError(
                "this checkpoint was saved without a serialized program "
                "(weights only); rebuild the model class and set_state_dict, or "
                "re-save with input_spec so paddle_tpu.jit.save exports one")
        raw = [a._value if isinstance(a, Tensor) else jax_asarray(a) for a in args]
        out = self._exported.call({k: t._value for k, t in self._state.items()}, *raw)
        import jax

        return jax.tree.map(Tensor, out) if not hasattr(out, "shape") else Tensor(out)

    def state_dict(self, *a, **k):
        return dict(self._state)

    @property
    def program_bytes(self):
        return self._meta.get("program_nbytes")


def _spec_to_aval(spec, scope_holder):
    """InputSpec/Tensor/ndarray → jax ShapeDtypeStruct; None dims become shared
    symbolic sizes so the exported program is batch-polymorphic."""
    import jax
    from jax import export as jexport

    if hasattr(spec, "_value"):  # Tensor example
        v = spec._value
        return jax.ShapeDtypeStruct(v.shape, v.dtype)
    if isinstance(spec, np.ndarray):
        return jax.ShapeDtypeStruct(spec.shape, spec.dtype)
    shape = []
    for i, d in enumerate(spec.shape):
        if d is None or (isinstance(d, int) and d < 0):
            name = f"d{len(scope_holder)}"
            if name not in scope_holder:
                scope_holder[name] = jexport.symbolic_shape(name)[0]
            shape.append(scope_holder[name])
        else:
            shape.append(d)
    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(spec.dtype))


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save: weights npz + serialized StableHLO program + meta.

    Reference: python/paddle/jit/api.py (save → TranslatedLayer contract).
    With `input_spec` (paddle.static.InputSpec / example Tensors) the forward
    is traced once and exported via jax.export — the artifact replays in a
    process that never imports the model class. Without input_spec the save is
    weights-only (load still works for set_state_dict flows).
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if isinstance(layer, StaticFunction):
        if input_spec is None:
            input_spec = layer._input_spec
        layer = layer.layer
    sd = layer.state_dict()
    state = {k: np.asarray(v._value) for k, v in sd.items()}
    np.savez(path + ".pdiparams.npz", **state)
    meta = {
        "class_name": type(layer).__name__,
        "state_keys": list(state.keys()),
        "has_program": False,
    }
    if input_spec is not None:
        import jax
        from jax import export as jexport

        was_training = layer.training
        layer.eval()
        try:
            def fwd(raw_state, *inputs):
                out = layer.functional_call(
                    raw_state, *[Tensor(x) for x in inputs])
                # Tensor is itself a registered pytree; unwrap at Tensor
                # granularity so the exported treedef holds only plain types
                return jax.tree.map(
                    lambda t: t._value if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor))

            scope: dict = {}
            state_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                           for k, v in state.items()}
            in_avals = [_spec_to_aval(s, scope) for s in input_spec]
            exported = jexport.export(jax.jit(fwd))(state_avals, *in_avals)
            blob = exported.serialize()
            with open(path + ".pdmodel", "wb") as f:
                f.write(blob)
            meta["has_program"] = True
            meta["program_nbytes"] = len(blob)
        finally:
            if was_training:
                layer.train()
    with open(path + ".pdmodel.meta", "wb") as f:
        pickle.dump(meta, f)


def load(path, **configs):
    """paddle.jit.load: returns a TranslatedLayer. If the artifact carries a
    serialized program, forward() replays it without the model class."""
    with open(path + ".pdmodel.meta", "rb") as f:
        meta = pickle.load(f)
    data = np.load(path + ".pdiparams.npz")
    state = {k: Tensor(jax_asarray(data[k])) for k in data.files}
    exported = None
    if meta.get("has_program") and os.path.exists(path + ".pdmodel"):
        from jax import export as jexport

        with open(path + ".pdmodel", "rb") as f:
            exported = jexport.deserialize(f.read())
    return TranslatedLayer(state, meta, exported)


def jax_asarray(a):
    import jax.numpy as jnp

    return jnp.asarray(a)


_verbosity = 0
_code_level = 0


def set_verbosity(level=0, also_to_stdout=False):
    """Reference: jit/sot/... set_verbosity — tracing-log verbosity. The
    trace-and-compile path has no bytecode translator; the knob gates the
    jit-layer debug logging."""
    global _verbosity
    _verbosity = int(level)


def set_code_level(level=100, also_to_stdout=False):
    """Reference: api.py set_code_level — print transformed code. There is no
    source transform here (tracing replaces dy2static); levels kept for
    script parity."""
    global _code_level
    _code_level = int(level)
