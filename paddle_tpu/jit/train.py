"""TrainStep: whole-training-step compilation — the TPU performance path.

Reference parity: this replaces the reference's static-graph Executor training path
(StandaloneExecutor over a Program, SURVEY.md §3.2) — forward, backward, grad clip and
optimizer update compile into ONE XLA program, so there is no per-op dispatch and XLA
fuses/overlaps everything (including GSPMD collectives when params/batch are sharded).

Works with any Layer + loss callable + paddle_tpu optimizer: optimizer accumulator
state is lifted into the jitted function's inputs/outputs by temporarily rebinding the
optimizer's accumulator store onto tracers (parameter ids are stable, so the same
`_update` rules run traced).
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import tape
from ..framework import random as _rng
from .fingerprint import aval_fingerprint
from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue
from ..nn.layer import Layer
from ..tensor import Tensor


def _functional_clip(grad_clip, grads: dict, params: dict):
    if grad_clip is None:
        return grads
    if isinstance(grad_clip, ClipGradByGlobalNorm):
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads.values())
        gnorm = jnp.sqrt(sq)
        scale = jnp.minimum(grad_clip.clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
        return {k: (g * scale).astype(g.dtype) for k, g in grads.items()}
    if isinstance(grad_clip, ClipGradByNorm):
        out = {}
        for k, g in grads.items():
            n = jnp.sqrt(jnp.sum(jnp.square(g)))
            out[k] = g * jnp.minimum(grad_clip.clip_norm / jnp.maximum(n, 1e-12), 1.0)
        return out
    if isinstance(grad_clip, ClipGradByValue):
        return {k: jnp.clip(g, grad_clip.min, grad_clip.max) for k, g in grads.items()}
    return grads


class TrainStep:
    """Compiled (loss, new_state) = step(batch).

    Usage:
        step = TrainStep(model, loss_fn, optimizer)   # loss_fn(outputs, labels)
        for x, y in loader:
            loss = step(x, y)                         # one XLA launch
    Parameter and accumulator updates are written back into the live Layer/optimizer
    objects after each call, so eval/save/load interop with the eager world.

    `in_shardings`: optional fn(name, value) -> jax sharding for params (hybrid
    parallel recipes hook in here); batch shardings via `batch_sharding`.
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer, donate_state=True,
                 return_outputs=False, split_label=False):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        # hapi metrics need the forward outputs: thread them out of the
        # compiled step as an aux (costs an extra device->host copy per call)
        self._return_outputs = return_outputs
        # split_label=True: the LAST positional arg is always the label — for
        # callers (hapi) that know, bypassing the forward-signature heuristic
        # (which misbinds labels into optional forward params like mask=None)
        self._split_label = split_label
        self._param_tensors = dict(model.state_dict())
        self._trainable = {
            k: t for k, t in self._param_tensors.items()
            if not t.stop_gradient and jnp.issubdtype(t.dtype, jnp.floating)
        }
        self._jitted = None
        self._compiled = None  # AOT executable installed by aot_prime()
        self._compiled_avals = None  # arg shapes/dtypes the AOT exe was built for
        self._monitor = None  # observability.training.StepMonitor.bind() target
        self._pending_monitor_counters = None  # checkpoint-restored counters
        # parked for a monitor that binds after import_state (the fit path)
        self._seed = 0
        # ZeRO stage recipe (dist.shard_optimizer(opt, ShardingStage1/2/3)):
        # enforced as shardings inside the compiled step — state in, grads mid,
        # state out — so the layout lives in ONE XLA program (reduce-scatter /
        # gather-on-use emitted by GSPMD), no eager relayout round-trips.
        self._stage = getattr(optimizer, "_shard_fn", None)
        if self._stage is not None and not hasattr(self._stage, "acc_sharding"):
            self._stage = None
        if self._stage is not None:
            for k, t in self._param_tensors.items():
                sh = self._stage.param_sharding(t)
                if sh is not None:
                    t._value = jax.device_put(t._value, sh)

    # -------------------------------------------------------------- traced step
    def _build(self):
        model = self.model
        opt = self.optimizer
        loss_fn = self.loss_fn
        trainable_keys = list(self._trainable)
        param_tensors = self._param_tensors
        return_outputs = self._return_outputs
        # map param name -> live Parameter object (ids stable across calls)
        inner_opt = getattr(opt, "_inner_opt", opt)
        stage = self._stage

        import inspect

        try:
            fwd_sig = inspect.signature(type(model).forward)
        except (TypeError, ValueError):
            fwd_sig = None

        def step_fn(state, acc_state, step_i, lr, key, args, kwargs):
            # Batch-splitting convention: if the model's forward can bind every arg,
            # it gets them all (models that compute loss internally, e.g.
            # GPTForCausalLM(input_ids, labels=...)); otherwise the last positional
            # arg is the label and goes to loss_fn (classifier + CrossEntropyLoss).
            model_args, label = args, None
            if self._split_label:
                model_args, label = args[:-1], args[-1]
            elif fwd_sig is not None:
                try:
                    fwd_sig.bind(model, *args, **kwargs)
                except TypeError:
                    model_args, label = args[:-1], args[-1]

            def loss_from(trainable_state):
                full = dict(state)
                full.update(trainable_state)
                mutated: dict = {}
                with _rng.trace_key(key), tape.no_grad():
                    out = model.functional_call(
                        full, *model_args, _capture_mutations=mutated, **kwargs
                    )
                    if label is not None:
                        loss_t = loss_fn(out, label)
                    elif isinstance(out, (tuple, list)):
                        loss_t = loss_fn(*out)
                    else:
                        loss_t = loss_fn(out)
                loss_v = loss_t._value if isinstance(loss_t, Tensor) else loss_t
                # auxiliary losses set by sublayers during THIS forward (MoE
                # gate load-balance l_aux) join the objective automatically —
                # without this, a user composing GPT+MoE silently trains with
                # no load balancing (reference wires gate.get_loss() the same
                # way). Freshness check: the attr must hold a tracer from the
                # live trace, not a stale concrete value from an eager call.
                for _l in model.sublayers(include_self=True):
                    _la = getattr(_l, "l_aux", None)
                    if _la is None:
                        continue
                    _lv = _la._value if isinstance(_la, Tensor) else _la
                    if isinstance(_lv, jax.core.Tracer):
                        loss_v = loss_v + _lv.astype(loss_v.dtype)
                # buffer updates (BN running mean/var) flow out as aux so they
                # survive functional_call's state restore
                buffers = {
                    k: (v._value if isinstance(v, Tensor) else v)
                    for k, v in mutated.items() if k not in trainable_keys
                }
                outs = None
                if return_outputs:
                    outs = jax.tree.map(
                        lambda t: (jax.lax.stop_gradient(t._value)
                                   if isinstance(t, Tensor) else t),
                        out, is_leaf=lambda t: isinstance(t, Tensor))
                return loss_v, (buffers, outs)

            trainable_state = {k: state[k] for k in trainable_keys}
            (loss_val, (new_buffers, fwd_outs)), grads = jax.value_and_grad(
                loss_from, has_aux=True
            )(trainable_state)
            if stage is not None and stage.shard_grads:
                # ZeRO-2/3: constrain gradient layout to the stage axis so the
                # dp gradient all-reduce lowers to reduce-scatter
                grads = {
                    k: (jax.lax.with_sharding_constraint(g, sh)
                        if (sh := stage.grad_sharding(tuple(g.shape))) is not None
                        else g)
                    for k, g in grads.items()
                }
            grads = _functional_clip(inner_opt._grad_clip, grads,
                                     trainable_state)
            # run optimizer update rules traced: swap accumulator store
            saved_acc = inner_opt._accumulators
            saved_step = inner_opt._step_count
            new_state = dict(state)
            try:
                # rebuild accumulator store with traced values keyed by live param ids
                traced_store: dict = {}
                for acc_name, per_param in acc_state.items():
                    traced_store[acc_name] = {
                        id(param_tensors[k]): v for k, v in per_param.items()
                    }
                inner_opt._accumulators = traced_store
                inner_opt._step_count = step_i
                for k in trainable_keys:
                    p = param_tensors[k]
                    g = grads[k]
                    pval = state[k]
                    plr = lr * p.optimize_attr.get("learning_rate", 1.0) if hasattr(
                        p, "optimize_attr") else lr
                    # pin the result to the param dtype: f32 lr scalars promote bf16
                    # params to f32 otherwise, silently retracing every step
                    new_state[k] = inner_opt._update(
                        p, pval, g.astype(pval.dtype), plr
                    ).astype(pval.dtype)
                new_acc = {
                    acc_name: {
                        k: traced_store[acc_name].get(id(param_tensors[k]))
                        for k in trainable_keys
                        if id(param_tensors[k]) in traced_store[acc_name]
                    }
                    for acc_name in traced_store
                }
            finally:
                inner_opt._accumulators = saved_acc
                inner_opt._step_count = saved_step
            new_state.update(new_buffers)
            if stage is not None:
                # pin output layouts: params (stage 3: sharded; stages 1-2:
                # replicated, or XLA would propagate the acc sharding onto them)
                # and optimizer state (stages 1-3: sharded)
                from jax.sharding import NamedSharding, PartitionSpec

                stage_mesh = stage._mesh()
                for k in trainable_keys:
                    psh = stage.param_sharding(param_tensors[k])
                    if psh is None and stage_mesh is not None and getattr(
                            param_tensors[k], "_dist_attr", None) is None:
                        psh = NamedSharding(stage_mesh.jax_mesh, PartitionSpec())
                    if psh is not None:
                        new_state[k] = jax.lax.with_sharding_constraint(
                            new_state[k], psh)
                for acc_name, per in new_acc.items():
                    for k, v in per.items():
                        if v is None:
                            continue
                        ash = stage.acc_sharding(param_tensors[k], tuple(v.shape))
                        if ash is not None:
                            per[k] = jax.lax.with_sharding_constraint(v, ash)
            if return_outputs:
                return loss_val, new_state, new_acc, fwd_outs
            return loss_val, new_state, new_acc

        return jax.jit(step_fn, donate_argnums=(0, 1))

    # ---------------------------------------------------- device-side multi-step
    def _build_scan(self, stacked_flags):
        """K steps inside ONE compiled program via lax.scan — the reference's
        Plan/Job executor shape (whole schedule device-side, SURVEY §3.2), and
        the antidote to per-call host dispatch: a host->device call carries
        ~2 buffers per parameter (state + accumulators); on tunneled PJRT
        transports that marshalling costs ~65 us/buffer and does NOT overlap
        device work (measured: a bare 66-param momentum update is 30 ms/step
        host-looped vs 3.1 ms inside fori_loop). Stacked batches ([K, ...],
        one slice per step) ride the scan xs; reused batches are closed over
        ONCE (no K-fold host-side broadcast copy); per-step RNG keys and LRs
        are precomputed arrays so the scan body is identical to a single
        __call__'s step_fn."""
        if self._jitted is None:
            self._jitted = self._build()
        step_fn = self._jitted.__wrapped__

        def scan_fn(state, acc_state, step_is, lrs, keys, scan_args,
                    const_args, kwargs):
            def body(carry, per_step):
                state, acc_state = carry
                step_i, lr, key, sliced = per_step
                it_s, it_c = iter(sliced), iter(const_args)
                args = tuple(next(it_s) if is_stacked else next(it_c)
                             for is_stacked in stacked_flags)
                out = step_fn(state, acc_state, step_i, lr, key, args, kwargs)
                loss_val, new_state, new_acc = out[:3]
                return (new_state, new_acc), loss_val

            (new_state, new_acc), losses = jax.lax.scan(
                body, (state, acc_state), (step_is, lrs, keys, scan_args))
            return losses, new_state, new_acc

        return jax.jit(scan_fn, donate_argnums=(0, 1), static_argnums=())

    def _prep_scan_inputs(self, n_steps, args, stacked, advance):
        """Shared assembly for run_steps/lowered_steps. `advance=True` bumps
        the optimizer step counter and RNG seed (a real run); False peeks."""
        inner_opt = getattr(self.optimizer, "_inner_opt", self.optimizer)
        state = {k: t._value for k, t in self._param_tensors.items()}
        acc_state = self._gather_acc_state()
        step0, seed0 = inner_opt._step_count, self._seed
        step_is, lrs, keys = [], [], []
        for i in range(n_steps):
            step_is.append(step0 + 1 + i)
            lrs.append(inner_opt.get_lr())
            keys.append(jax.random.fold_in(_rng.default_generator().base_key(),
                                           seed0 + 1 + i))
        if advance:
            inner_opt._step_count = step0 + n_steps
            self._seed = seed0 + n_steps

        vals = tuple(a._value if isinstance(a, Tensor) else jnp.asarray(a)
                     for a in args)
        if stacked:
            for v in vals:
                if v.ndim == 0 or v.shape[0] != n_steps:
                    raise ValueError(
                        f"stacked=True: every batch arg needs leading dim "
                        f"{n_steps}, got shape {v.shape}")
        flags = tuple(bool(stacked) for _ in vals)
        scan_args = tuple(v for v, f in zip(vals, flags) if f)
        const_args = tuple(v for v, f in zip(vals, flags) if not f)
        return (inner_opt, state, acc_state,
                jnp.asarray(step_is, jnp.int32),
                jnp.asarray(lrs, jnp.float32), jnp.stack(keys),
                scan_args, const_args, flags)

    def _scanned_for(self, flags):
        cache = getattr(self, "_scan_cache", None)
        if cache is None:
            cache = self._scan_cache = {}
        fn = cache.get(flags)
        if fn is None:
            fn = cache[flags] = self._build_scan(flags)
        return fn

    def run_steps(self, n_steps: int, *args, stacked=False, **kwargs):
        """Run `n_steps` training steps in one device-side program.

        `stacked=True`: every positional batch arg carries a leading
        dim of `n_steps` — one slice per step. `stacked=False` (default):
        the same batch is reused every step (closed over in-program — no
        K-fold copy). Returns per-step losses as a Tensor [K]. Numerics match
        n_steps sequential __call__s exactly: the same step counters, LR
        values and RNG key derivations are precomputed per step.
        """
        if self._return_outputs:
            raise ValueError("run_steps does not support return_outputs=True")
        mon = self._monitor
        t0 = mon.step_begin() if mon is not None else None
        (inner_opt, state, acc_state, step_is, lrs, keys, scan_args,
         const_args, flags) = self._prep_scan_inputs(n_steps, args, stacked,
                                                     advance=True)
        if mon is not None:
            mon.before_scan_launch(self, n_steps, flags, args, kwargs, t0)
        losses, new_state, new_acc = self._scanned_for(flags)(
            state, acc_state, step_is, lrs, keys, scan_args, const_args,
            kwargs)
        for k, t in self._param_tensors.items():
            t._value = new_state[k]
        for acc_name, per in new_acc.items():
            store = inner_opt._accumulators.setdefault(acc_name, {})
            for k, v in per.items():
                store[id(self._param_tensors[k])] = v
        if mon is not None:
            mon.step_end(self, losses[-1], t0, n_steps=n_steps)
        return Tensor(losses)

    def lowered_steps(self, n_steps: int, *args, stacked=False, **kwargs):
        """AOT-lower run_steps for cost_analysis (flops are for ALL n_steps)."""
        (_, state, acc_state, step_is, lrs, keys, scan_args, const_args,
         flags) = self._prep_scan_inputs(n_steps, args, stacked, advance=False)
        return self._scanned_for(flags).lower(
            state, acc_state, step_is, lrs, keys, scan_args, const_args,
            kwargs)

    def _gather_acc_state(self):
        inner_opt = getattr(self.optimizer, "_inner_opt", self.optimizer)
        acc = {}
        for acc_name, store in inner_opt._accumulators.items():
            per = {}
            for k, t in self._param_tensors.items():
                if id(t) in store:
                    per[k] = store[id(t)]
            acc[acc_name] = per
        # materialize zero-init accumulators on first call so the traced shapes exist
        if not acc:
            names = getattr(inner_opt, "_acc_names", ())
            acc_init = getattr(inner_opt, "_acc_init",
                               lambda name, v: jnp.zeros_like(v))
            for acc_name in names:
                if acc_name == "moment2_max" and not getattr(inner_opt, "_amsgrad", False):
                    continue
                acc[acc_name] = {
                    k: acc_init(acc_name, t._value)
                    for k, t in self._trainable.items()
                }
            if self._stage is not None:
                for acc_name, per in acc.items():
                    for k, v in per.items():
                        sh = self._stage.acc_sharding(self._param_tensors[k],
                                                      tuple(v.shape))
                        if sh is not None:
                            per[k] = jax.device_put(v, sh)
        return acc

    # ------------------------------------------------- checkpoint state hooks
    def export_state(self):
        """Everything a bit-exact resume needs, as live array refs + a
        JSON-able ``meta`` — the ``framework.checkpoint.CheckpointManager``
        provider contract. Cheap (no copies): the manager host-materializes
        immediately, before the next step can donate these buffers."""
        inner_opt = getattr(self.optimizer, "_inner_opt", self.optimizer)
        state = {
            "params": {k: t._value for k, t in self._param_tensors.items()},
            "acc": self._gather_acc_state(),
        }
        mw = getattr(inner_opt, "_master_weights", None)
        if mw:
            by_id = {id(t): k for k, t in self._param_tensors.items()}
            state["master"] = {by_id[pid]: v for pid, v in mw.items()
                               if pid in by_id}
        meta = {
            "step_count": int(inner_opt._step_count),
            "seed": int(self._seed),
            "rng": list(_rng.get_rng_state()),
        }
        from ..optimizer.lr import LRScheduler

        if isinstance(inner_opt._learning_rate, LRScheduler):
            meta["lr_sched"] = inner_opt._learning_rate.state_dict()
        if self._monitor is not None:
            counters = getattr(self._monitor, "export_counters", None)
            if counters is not None:
                meta["monitor"] = counters()
        state["meta"] = meta
        return state

    def import_state(self, state):
        """Reverse of ``export_state``: rebuild params/accumulators/counters
        so the NEXT step reproduces what an uninterrupted run would have
        computed, bit for bit. Values land with the avals (shape/dtype) and
        shardings of the current state, so the cached executable (jit cache
        or AOT) is reused — restoring never recompiles."""
        inner_opt = getattr(self.optimizer, "_inner_opt", self.optimizer)
        for k, t in self._param_tensors.items():
            v = state.get("params", {}).get(k)
            if v is not None:
                t._value = self._place_like(v, t._value)
        for acc_name, per in (state.get("acc") or {}).items():
            store = inner_opt._accumulators.setdefault(acc_name, {})
            for k, v in per.items():
                t = self._param_tensors.get(k)
                if t is None:
                    continue
                cur = store.get(id(t))
                val = self._place_like(v, cur)
                if self._stage is not None:
                    sh = self._stage.acc_sharding(t, tuple(val.shape))
                    if sh is not None:
                        val = jax.device_put(val, sh)
                store[id(t)] = val
        if state.get("master"):
            mw = getattr(inner_opt, "_master_weights", None)
            if mw is not None:
                for k, v in state["master"].items():
                    t = self._param_tensors.get(k)
                    if t is not None:
                        mw[id(t)] = self._place_like(v, mw.get(id(t)))
        meta = state.get("meta") or {}
        if "step_count" in meta:
            inner_opt._step_count = int(meta["step_count"])
        if "seed" in meta:
            self._seed = int(meta["seed"])
        if "rng" in meta:
            _rng.set_rng_state(tuple(meta["rng"]))
        if "lr_sched" in meta:
            from ..optimizer.lr import LRScheduler

            if isinstance(inner_opt._learning_rate, LRScheduler):
                inner_opt._learning_rate.set_state_dict(meta["lr_sched"])
        if "monitor" in meta:
            if self._monitor is not None:
                importer = getattr(self._monitor, "import_counters", None)
                if importer is not None:
                    importer(meta["monitor"])
            else:
                # no monitor bound yet (fit binds via MonitorCallback on the
                # first batch, AFTER restore): park the counters for bind()
                self._pending_monitor_counters = dict(meta["monitor"])

    @staticmethod
    def _place_like(value, current):
        """Device-place a restored array with the dtype/sharding of the live
        value it replaces — the aval must not change or the next launch
        retraces (the recompile sentinel pins this in tests)."""
        if current is None:
            return jnp.asarray(value)
        dtype = getattr(current, "dtype", None)
        arr = np.asarray(value) if not isinstance(value, jax.Array) else value
        if dtype is not None and arr.dtype != dtype:
            arr = arr.astype(dtype)
        if isinstance(current, jax.Array) and not isinstance(
                current, jax.core.Tracer):
            try:
                return jax.device_put(arr, current.sharding)
            except Exception:  # pragma: no cover - exotic placement
                pass
        return jnp.asarray(arr)

    def _prep_inputs(self, advance: bool):
        """Build the exact traced-input tuple a step consumes. `advance=True` bumps
        the step counter / RNG seed (a real step); `advance=False` peeks at what the
        NEXT call would pass (AOT lowering for audit), mutating nothing."""
        if self._jitted is None:
            self._jitted = self._build()
        inner_opt = getattr(self.optimizer, "_inner_opt", self.optimizer)
        state = {k: t._value for k, t in self._param_tensors.items()}
        acc_state = self._gather_acc_state()
        if advance:
            inner_opt._step_count += 1
            self._seed += 1
            seed, step_count = self._seed, inner_opt._step_count
        else:
            seed, step_count = self._seed + 1, inner_opt._step_count + 1
        key = jax.random.fold_in(_rng.default_generator().base_key(), seed)
        step_i = jnp.asarray(step_count, jnp.int32)
        lr = jnp.asarray(inner_opt.get_lr(), jnp.float32)
        return inner_opt, (state, acc_state, step_i, lr, key)

    def lowered(self, *args, **kwargs):
        """AOT-lower the compiled step for the same (args, kwargs) a __call__ would
        see — for `compile().cost_analysis()` (FLOPs/MFU audit) without executing a
        step or mutating optimizer bookkeeping."""
        _, traced = self._prep_inputs(advance=False)
        return self._jitted.lower(*traced, args, kwargs)

    def aot_prime(self, *args, **kwargs):
        """Compile once ahead-of-time and install the executable so subsequent
        __call__s reuse it (avoids the separate jit-cache compile). Returns the
        jax compiled object (cost_analysis(), as_text())."""
        self._compiled = self.lowered(*args, **kwargs).compile()
        self._compiled_avals = self._arg_avals(args, kwargs)
        return self._compiled

    # one fingerprint definition shared with the serving warmup/sentinel
    # (jit/fingerprint.py) so the two recompile sentinels cannot drift
    _arg_avals = staticmethod(aval_fingerprint)

    def __call__(self, *args, **kwargs):
        mon = self._monitor
        t0 = mon.step_begin() if mon is not None else None
        inner_opt, traced = self._prep_inputs(advance=True)
        fn = self._jitted
        aot_hit = False
        if self._compiled is not None:
            # the AOT executable is shape-specialised; a different batch shape
            # must fall back to the jitted path (which recompiles) not raise
            if self._arg_avals(args, kwargs) == self._compiled_avals:
                fn = self._compiled
                aot_hit = True
        if mon is not None:
            # h2d span closes + recompile sentinel fingerprints the avals
            # (catching the aot-fallback recompile right above)
            mon.before_launch(self, args, kwargs, aot_hit, t0)
        result = fn(*traced, args, kwargs)
        if self._return_outputs:
            loss_val, new_state, new_acc, fwd_outs = result
        else:
            (loss_val, new_state, new_acc), fwd_outs = result, None
        # write back into live objects
        for k, t in self._param_tensors.items():
            t._value = new_state[k]
        for acc_name, per in new_acc.items():
            store = inner_opt._accumulators.setdefault(acc_name, {})
            for k, v in per.items():
                store[id(self._param_tensors[k])] = v
        if mon is not None:
            mon.step_end(self, loss_val, t0)
        if self._return_outputs:
            outs = jax.tree.map(Tensor, fwd_outs)
            return Tensor(loss_val), outs
        return Tensor(loss_val)
