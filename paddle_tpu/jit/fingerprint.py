"""Shared abstract-value fingerprinting for the recompile sentinels.

Both sentinels answer the same question — "would this launch re-trace?" —
by fingerprinting the launch arguments down to (treedef, shape, dtype):

* training: ``TrainStep`` keys its AOT-hit check on it and the PR 4
  ``StepMonitor`` sentinel fingerprints every ``__call__`` to count
  ``paddle_train_recompiles_total`` (observability/training.py);
* serving: the ISSUE-13 ``AOTWarmup`` (inference/warmup.py) fingerprints
  the step-program launches it pre-compiles, so a post-ready cold build
  can be reported against the exact avals the warmup covered.

One helper, one definition: the two sentinels cannot drift on what counts
as "the same shape".
"""
from __future__ import annotations

import jax


def aval_fingerprint(args, kwargs=None):
    """(treedef, ((shape, dtype), ...)) over the flattened (args, kwargs).

    Non-array leaves fingerprint as (None, type name) — value-insensitive
    on purpose: jit traces plain Python scalars as weak-typed arrays, so a
    changed int does NOT retrace and must not change the print. A changed
    leaf TYPE, container structure, array shape, or dtype does.
    """
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    return (
        treedef,
        tuple((getattr(x, "shape", None), str(getattr(x, "dtype", type(x))))
              for x in leaves),
    )
