"""paddle.linalg namespace. Reference: python/paddle/linalg.py (38 exports)."""
from .ops.linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond, corrcoef, cov, det, eig, eigh, eigvals, eigvalsh,
    householder_product, inverse as inv, lstsq, lu, matmul, matrix_exp, matrix_power,
    matrix_rank, multi_dot, norm, pinv, qr, slogdet, solve, svd, triangular_solve,
    vecdot,
)
from .ops.linalg import matrix_norm, vector_norm  # noqa: F401
