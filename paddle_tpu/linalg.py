"""paddle.linalg namespace. Reference: python/paddle/linalg.py (38 exports).

Complete re-export of ops.linalg (importing this module rebinds the package
attribute `paddle_tpu.linalg` away from ops.linalg, so it must be a superset,
not a curated subset) plus the paddle-specific aliases (`inv`) and the round-5
matrix_norm/vector_norm with reference axis/ord semantics."""
from .ops.linalg import *  # noqa: F401,F403
from .ops.linalg import __all__ as _ops_all
from .ops.linalg import inverse as inv  # noqa: F401
from .ops.linalg import matrix_norm, vector_norm  # noqa: F401

__all__ = sorted(set(_ops_all) | {"inv", "matrix_norm", "vector_norm"})
