"""Optimizers. Reference: python/paddle/optimizer/ (17 files).

Each optimizer keeps raw jax-array state ("accumulators") keyed by parameter identity and
exposes paddle's API: step()/minimize()/clear_grad(). The update math is pure jnp — under
the functional training path the same `_update` rules run inside one jitted step.
"""
from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from ..autograd import no_grad
from ..nn.clip import ClipGradBase
from ..tensor import Tensor
from . import lr as lr_mod
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
           "Adadelta", "RMSProp", "Lamb", "LBFGS", "lr", "ASGD", "NAdam",
           "RAdam", "Rprop"]


def _pow_step(base, t):
    """``base ** t`` for a step counter that may be a TRACED int32 inside a
    compiled TrainStep. Python-float ** int-array lands in STRONG float64
    under the framework's global x64, and the f64 scalar then promotes the
    whole bias-corrected moment math to f64 (slow/emulated on TPU — the
    graph linter's dtype-upcast rule flags exactly this). Traced counters
    therefore compute the pow as an f32 scalar (the RAdam idiom); eager
    Python ints keep exact Python-float math."""
    if isinstance(t, jax.core.Tracer) or hasattr(t, "dtype"):
        return jnp.power(jnp.float32(base), jnp.asarray(t, jnp.float32))
    return base ** t


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        if parameters is None:
            raise ValueError("parameters must be provided (dygraph mode)")
        # support param groups: list of dicts with 'params' key
        self._param_groups = []
        params = list(parameters)
        if params and isinstance(params[0], dict):
            for g in params:
                grp = dict(g)
                grp["params"] = list(g["params"])
                self._param_groups.append(grp)
        else:
            self._param_groups.append({"params": params})
        self._learning_rate = learning_rate
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators: dict[str, dict[int, Any]] = {}
        self._master_weights: dict[int, Any] = {}
        self._step_count = 0

    # ------------------------------------------------------------------ lr
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = value

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    def _parameters_list(self):
        for group in self._param_groups:
            for p in group["params"]:
                yield group, p

    # ------------------------------------------------------------------ accumulators
    def _acc_init(self, name, pval):
        """Initial accumulator value for `name` given the parameter payload —
        overridable for non-parameter-shaped state (ASGD's grad ring buffer,
        NAdam's scalar momentum product); consulted by both the eager path
        and TrainStep's accumulator materialization."""
        return jnp.zeros_like(pval)

    def _acc(self, name, p, init=None):
        store = self._accumulators.setdefault(name, {})
        if id(p) not in store:
            store[id(p)] = init if init is not None else self._acc_init(
                name, p._value)
        return store[id(p)]

    def _set_acc(self, name, p, value):
        self._accumulators[name][id(p)] = value

    # ------------------------------------------------------------------ main api
    @no_grad()
    def step(self):
        params_grads = []
        for group, p in self._parameters_list():
            if p.stop_gradient or p._grad is None:
                continue
            params_grads.append((p, p.grad))
        if self._grad_clip is not None and isinstance(self._grad_clip, ClipGradBase):
            params_grads = self._grad_clip(params_grads)
        self._step_count += 1
        for p, g in params_grads:
            if g is None:
                continue
            lr = self.get_lr() * p.optimize_attr.get("learning_rate", 1.0) if hasattr(
                p, "optimize_attr") else self.get_lr()
            gval = g._value.astype(jnp.float32) if self._multi_precision else g._value
            pval = p._value
            if self._multi_precision and jnp.issubdtype(pval.dtype, jnp.floating) and \
                    pval.dtype != jnp.float32:
                if id(p) not in self._master_weights:
                    self._master_weights[id(p)] = pval.astype(jnp.float32)
                master = self._master_weights[id(p)]
                new_master = self._update(p, master, gval, lr)
                self._master_weights[id(p)] = new_master
                p._value = new_master.astype(pval.dtype)
            else:
                p._value = self._update(
                    p, pval, gval.astype(pval.dtype), lr
                ).astype(pval.dtype)

    def _update(self, p, pval, g, lr):
        raise NotImplementedError

    def _apply_decay(self, p, pval, g):
        """L2 regularization folded into the gradient (paddle's default weight_decay
        semantics for non-AdamW optimizers). Per-param regularizer overrides the
        optimizer-level coefficient (reference behavior)."""
        wd = getattr(p, "regularizer", None)
        if wd is None:
            wd = self._weight_decay
        if wd is None:
            return g
        if hasattr(wd, "_coeff"):
            wd = wd._coeff
        if isinstance(wd, (int, float)) and wd != 0.0:
            return g + jnp.asarray(wd, g.dtype) * pval
        return g

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, [(p, p.grad) for _, p in self._parameters_list()]

    def clear_grad(self, set_to_zero=False):
        for _, p in self._parameters_list():
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    # ------------------------------------------------------------------ state dict
    def state_dict(self):
        out = {}
        names = self._param_names()
        for acc_name, store in self._accumulators.items():
            for pid, val in store.items():
                out[f"{names.get(pid, pid)}_{acc_name}"] = Tensor(val)
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        out["@step"] = self._step_count
        return out

    def set_state_dict(self, state):
        names = {v: k for k, v in self._param_names().items()}
        self._step_count = int(state.get("@step", 0))
        if "LR_Scheduler" in state and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])
        # Accumulators are created lazily by step(); restoring before the first step
        # must still land, so match against the class-declared accumulator names too.
        acc_names = set(self._accumulators) | set(getattr(self, "_acc_names", ()))
        for key, val in state.items():
            if key in ("@step", "LR_Scheduler"):
                continue
            for acc_name in acc_names:
                suffix = "_" + acc_name
                if key.endswith(suffix):
                    pname = key[: -len(suffix)]
                    if pname in names:
                        self._accumulators.setdefault(acc_name, {})[names[pname]] = (
                            val._value if isinstance(val, Tensor) else jnp.asarray(val)
                        )
                    break

    def _param_names(self):
        return {id(p): p.name for _, p in self._parameters_list()}


class SGD(Optimizer):
    _acc_names = ()

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         multi_precision)

    def _update(self, p, pval, g, lr):
        g = self._apply_decay(p, pval, g)
        return pval - lr * g


class Momentum(Optimizer):
    _acc_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _update(self, p, pval, g, lr):
        g = self._apply_decay(p, pval, g)
        v = self._acc("velocity", p)
        v = self._momentum * v + g
        self._set_acc("velocity", p, v)
        if self._nesterov:
            return pval - lr * (g + self._momentum * v)
        return pval - lr * v


class Adam(Optimizer):
    _acc_names = ("moment1", "moment2", "moment2_max")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, use_multi_tensor=False, amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._amsgrad = amsgrad

    def _beta_pows(self, p):
        t = self._step_count
        b1 = self._beta1 if not isinstance(self._beta1, Tensor) else float(self._beta1.item())
        b2 = self._beta2 if not isinstance(self._beta2, Tensor) else float(self._beta2.item())
        return b1, b2, _pow_step(b1, t), _pow_step(b2, t)

    def _update(self, p, pval, g, lr):
        g = self._apply_decay(p, pval, g)
        b1, b2, b1p, b2p = self._beta_pows(p)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * jnp.square(g)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        if self._amsgrad:
            vmax = self._acc("moment2_max", p)
            vmax = jnp.maximum(vmax, v)
            self._set_acc("moment2_max", p, vmax)
            vv = vmax
        else:
            vv = v
        mhat = m / (1 - b1p)
        vhat = vv / (1 - b2p)
        return pval - lr * mhat / (jnp.sqrt(vhat) + self._eps)


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, amsgrad=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters, None,
                         grad_clip, lazy_mode, multi_precision, amsgrad=amsgrad, name=name)
        self._wd_coeff = weight_decay if not hasattr(weight_decay, "_coeff") else weight_decay._coeff
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _update(self, p, pval, g, lr):
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        decay = self._wd_coeff
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            decay = 0.0
        b1, b2, b1p, b2p = self._beta_pows(p)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * jnp.square(g)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        if self._amsgrad:
            vmax = jnp.maximum(self._acc("moment2_max", p), v)
            self._set_acc("moment2_max", p, vmax)
            vv = vmax
        else:
            vv = v
        mhat = m / (1 - b1p)
        vhat = vv / (1 - b2p)
        pnew = pval * (1.0 - lr * decay)
        return pnew - lr * mhat / (jnp.sqrt(vhat) + self._eps)


class Adamax(Optimizer):
    _acc_names = ("moment", "inf_norm")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _update(self, p, pval, g, lr):
        g = self._apply_decay(p, pval, g)
        t = self._step_count
        m = self._acc("moment", p)
        u = self._acc("inf_norm", p)
        m = self._beta1 * m + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * u, jnp.abs(g))
        self._set_acc("moment", p, m)
        self._set_acc("inf_norm", p, u)
        return pval - lr / (1 - _pow_step(self._beta1, t)) * m / (u + self._eps)


class Adagrad(Optimizer):
    _acc_names = ("moment",)

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _update(self, p, pval, g, lr):
        g = self._apply_decay(p, pval, g)
        acc = self._acc("moment", p, jnp.full_like(p._value, self._init_acc))
        acc = acc + jnp.square(g)
        self._set_acc("moment", p, acc)
        return pval - lr * g / (jnp.sqrt(acc) + self._eps)


class Adadelta(Optimizer):
    _acc_names = ("avg_squared_grad", "avg_squared_update")

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps, self._rho = epsilon, rho

    def _update(self, p, pval, g, lr):
        g = self._apply_decay(p, pval, g)
        avg_sq = self._acc("avg_squared_grad", p)
        avg_upd = self._acc("avg_squared_update", p)
        avg_sq = self._rho * avg_sq + (1 - self._rho) * jnp.square(g)
        delta = jnp.sqrt(avg_upd + self._eps) / jnp.sqrt(avg_sq + self._eps) * g
        avg_upd = self._rho * avg_upd + (1 - self._rho) * jnp.square(delta)
        self._set_acc("avg_squared_grad", p, avg_sq)
        self._set_acc("avg_squared_update", p, avg_upd)
        return pval - lr * delta


class RMSProp(Optimizer):
    _acc_names = ("mean_square", "mean_grad", "momentum")

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._eps, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _update(self, p, pval, g, lr):
        g = self._apply_decay(p, pval, g)
        ms = self._acc("mean_square", p)
        ms = self._rho * ms + (1 - self._rho) * jnp.square(g)
        self._set_acc("mean_square", p, ms)
        if self._centered:
            mg = self._acc("mean_grad", p)
            mg = self._rho * mg + (1 - self._rho) * g
            self._set_acc("mean_grad", p, mg)
            denom = jnp.sqrt(ms - jnp.square(mg) + self._eps)
        else:
            denom = jnp.sqrt(ms + self._eps)
        mom = self._acc("momentum", p)
        mom = self._momentum * mom + lr * g / denom
        self._set_acc("momentum", p, mom)
        return pval - mom


class Lamb(Optimizer):
    _acc_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update(self, p, pval, g, lr):
        t = self._step_count
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        mhat = m / (1 - _pow_step(self._beta1, t))
        vhat = v / (1 - _pow_step(self._beta2, t))
        r = mhat / (jnp.sqrt(vhat) + self._eps)
        wd = self._lamb_wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        update = r + wd * pval
        w_norm = jnp.linalg.norm(pval.reshape(-1).astype(jnp.float32))
        u_norm = jnp.linalg.norm(update.reshape(-1).astype(jnp.float32))
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0).astype(pval.dtype)
        return pval - lr * trust * update


class LBFGS(Optimizer):
    """Minimal LBFGS (reference: python/paddle/optimizer/lbfgs.py) — closure-based."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._max_iter = max_iter
        self._history_size = history_size
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history = []  # list of (s, y, rho)
        self._prev_flat_grad = None
        self._prev_flat_w = None

    def _flatten(self, vals):
        return jnp.concatenate([v.reshape(-1) for v in vals])

    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure")
        params = [p for _, p in self._parameters_list()]
        loss = closure()
        flat_g = self._flatten([
            p._grad if p._grad is not None else jnp.zeros_like(p._value)
            for p in params
        ]).astype(jnp.float32)
        flat_w = self._flatten([p._value for p in params]).astype(jnp.float32)
        if self._prev_flat_grad is not None:
            s = flat_w - self._prev_flat_w
            y = flat_g - self._prev_flat_grad
            ys = float(jnp.dot(y, s))
            if ys > 1e-10:
                self._history.append((s, y, 1.0 / ys))
                if len(self._history) > self._history_size:
                    self._history.pop(0)
        q = flat_g
        alphas = []
        for s, y, rho in reversed(self._history):
            a = rho * jnp.dot(s, q)
            alphas.append(a)
            q = q - a * y
        if self._history:
            s, y, rho = self._history[-1]
            gamma = jnp.dot(s, y) / jnp.dot(y, y)
            q = q * gamma
        for (s, y, rho), a in zip(self._history, reversed(alphas)):
            b = rho * jnp.dot(y, q)
            q = q + (a - b) * s
        direction = -q
        lr = self.get_lr()
        new_w = flat_w + lr * direction
        offset = 0
        for p in params:
            n = p.size
            p._value = new_w[offset:offset + n].reshape(p._value.shape).astype(p._value.dtype)
            offset += n
        self._prev_flat_grad = flat_g
        self._prev_flat_w = flat_w
        self._step_count += 1
        return loss


class ASGD(Optimizer):
    """Reference: python/paddle/optimizer/asgd.py — averaged SGD: maintains a
    running average of the last n gradients (paddle's formulation: d = sum of
    the n most recent grads; update uses d/n)."""

    _acc_names = ("d", "ys")

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._n = max(1, int(batch_num))

    def _acc_init(self, name, pval):
        if name == "ys":
            return jnp.zeros((self._n,) + tuple(pval.shape), pval.dtype)
        return jnp.zeros_like(pval)

    def _update(self, p, pval, g, lr):
        g = self._apply_decay(p, pval, g)
        # ring buffer of the last n grads, summarized by the running sum d
        i = (self._step_count - 1) % self._n
        ys = self._acc("ys", p)
        d = self._acc("d", p)
        d = d - ys[i] + g
        ys = ys.at[i].set(g)
        self._set_acc("d", p, d)
        self._set_acc("ys", p, ys)
        seen = jnp.minimum(jnp.asarray(self._step_count, jnp.float32),
                           float(self._n))
        return pval - lr * d / seen


class Rprop(Optimizer):
    """Reference: python/paddle/optimizer/rprop.py — resilient backprop:
    per-element step sizes grown/shrunk by gradient sign agreement (full-batch
    regime)."""

    _acc_names = ("prev_grad", "step_size")

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_minus, self._eta_plus = etas

    def _acc_init(self, name, pval):
        if name == "step_size":
            return jnp.full(pval.shape, float(self.get_lr()), jnp.float32)
        return jnp.zeros_like(pval)

    def _update(self, p, pval, g, lr):
        prev = self._acc("prev_grad", p)
        step = self._acc("step_size", p)
        sign = jnp.sign(g * prev)
        grow = (sign > 0).astype(jnp.float32)
        shrink = (sign < 0).astype(jnp.float32)
        same = (sign == 0).astype(jnp.float32)
        step = jnp.clip(step * (grow * self._eta_plus
                                + shrink * self._eta_minus + same),
                        self._lr_min, self._lr_max)
        # on sign flip: revert gradient to 0 (iRprop- variant, matching the
        # reference's sign-based update with no weight-backtracking)
        g_eff = jnp.where(sign < 0, 0.0, g)
        self._set_acc("prev_grad", p, g_eff)
        self._set_acc("step_size", p, step)
        return pval - jnp.sign(g_eff).astype(pval.dtype) * step.astype(pval.dtype)


class NAdam(Adam):
    """Reference: python/paddle/optimizer/nadam.py — Adam with Nesterov
    momentum (Dozat 2016): the momentum schedule mu_t folds the lookahead
    into the first-moment estimate."""

    _acc_names = ("moment1", "moment2", "mu_prod")

    def _acc_init(self, name, pval):
        if name == "mu_prod":
            return jnp.ones((), jnp.float32)
        return jnp.zeros_like(pval)

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, multi_precision=multi_precision,
                         name=name)
        self._psi = momentum_decay

    def _update(self, p, pval, g, lr):
        g = self._apply_decay(p, pval, g)
        t = self._step_count
        b1, b2 = self._beta1, self._beta2
        mu_t = b1 * (1 - 0.5 * _pow_step(0.96, t * self._psi))
        mu_t1 = b1 * (1 - 0.5 * _pow_step(0.96, (t + 1) * self._psi))
        prods = self._acc("mu_prod", p)
        mu_prod = prods * mu_t
        self._set_acc("mu_prod", p, mu_prod)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * jnp.square(g)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        mhat = (mu_t1 * m / (1 - mu_prod * mu_t1)
                + (1 - mu_t) * g / (1 - mu_prod))
        vhat = v / (1 - _pow_step(b2, t))
        return pval - lr * mhat / (jnp.sqrt(vhat) + self._eps)


class RAdam(Adam):
    """Reference: python/paddle/optimizer/radam.py — rectified Adam: falls
    back to un-adapted SGD-with-momentum while the variance estimate is
    unreliable (small t), then switches on the rectification term."""

    def _update(self, p, pval, g, lr):
        g = self._apply_decay(p, pval, g)
        t = self._step_count
        b1, b2 = self._beta1, self._beta2
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * jnp.square(g)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        mhat = m / (1 - _pow_step(b1, t))
        rho_inf = 2.0 / (1 - b2) - 1.0
        # t may be a traced step counter inside TrainStep: branch via where
        tf = jnp.asarray(t, jnp.float32)
        b2t = jnp.power(jnp.float32(b2), tf)
        rho_t = rho_inf - 2.0 * tf * b2t / (1 - b2t)
        vhat = jnp.sqrt(v / (1 - b2t))
        rect_num = jnp.maximum((rho_t - 4) * (rho_t - 2) * rho_inf, 0.0)
        r = jnp.sqrt(rect_num / ((rho_inf - 4) * (rho_inf - 2)
                                 * jnp.maximum(rho_t, 1e-6)))
        adapted = pval - lr * r * mhat / (vhat + self._eps)
        plain = pval - lr * mhat
        return jnp.where(rho_t > 5.0, adapted, plain)
