"""paddle.static.nn — traceable control flow (reference: python/paddle/static/nn/__init__.py:37)."""
from .control_flow import (  # noqa: F401
    Assert, Print, case, cond, switch_case, while_loop,
)

__all__ = ["case", "cond", "switch_case", "while_loop"]
