"""Traceable control flow: while_loop / cond / case / switch_case / Assert / Print.

Reference: python/paddle/static/nn/control_flow.py (while_loop:755, case:1062,
switch_case:1185, cond:1637, Assert:59, Print:2215). The reference builds
sub-block ops (While/ConditionalBlock/select_input) into a static Program; the
TPU-native design has no Program — instead each construct has dual behavior:

- **Eager** (all predicates concrete): plain Python control flow. The chosen
  branch / loop body runs through the normal op layer, so tape autograd works
  through it unchanged (this matches the reference's dygraph branch, which also
  just evaluates the predicate and calls one fn).
- **Traced** (a predicate is a jax tracer, i.e. inside ``paddle.jit.to_static``
  or any jit): lowers to ``lax.while_loop`` / ``lax.cond`` / ``lax.switch`` so
  data-dependent control flow compiles into the XLA program instead of raising
  (closes the round-3 dy2static gap). Branches/bodies execute on Tensors that
  wrap tracers; tape recording is disabled inside (reverse-mode AD through a
  traced while_loop is not supported — same restriction as lax).
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
from jax import lax

from ...autograd import tape
from ...tensor import Tensor

__all__ = ["Assert", "Print", "case", "cond", "switch_case", "while_loop"]


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _is_tensor_leaf(x):
    return isinstance(x, Tensor)


def _flatten(nest):
    """Flatten a nest of Tensors (list/tuple/dict allowed) to jax arrays."""
    leaves, treedef = jax.tree_util.tree_flatten(
        nest, is_leaf=_is_tensor_leaf
    )
    arrays = [jnp.asarray(_unwrap(leaf)) for leaf in leaves]
    return arrays, treedef


def _rebuild(arrays, treedef):
    tensors = [Tensor(a, stop_gradient=True) for a in arrays]
    return jax.tree_util.tree_unflatten(treedef, tensors)


def _scalar_bool(x):
    """Predicate Tensor/array -> scalar jax bool (shape [] or [1] accepted)."""
    v = jnp.asarray(_unwrap(x))
    if v.ndim > 0:
        v = v.reshape(())
    return v.astype(jnp.bool_)


def _is_traced(*preds) -> bool:
    return builtins.any(
        isinstance(jnp.asarray(_unwrap(p)), jax.core.Tracer) for p in preds
    )


def _check_dtypes(got, want, got_name, want_name):
    for g, w in zip(got, want):
        if g.dtype != w.dtype:
            raise ValueError(
                f"{got_name} output dtype {g.dtype} does not match "
                f"{want_name} dtype {w.dtype}; branches/bodies must return "
                "identical dtypes (cast explicitly)")


def _none_fn():
    return None


def _probe(fn):
    """Trace `fn` abstractly (no ops emitted) to learn its output structure."""
    box = []

    def probe():
        arrays, td = _flatten(fn())
        box.append(td)
        return tuple(arrays)

    specs = jax.eval_shape(probe)
    return box[0], list(specs)


def _debug_callbacks_supported() -> bool:
    # the axon TPU PJRT plugin rejects host send/recv callbacks; debug.print
    # inside a compiled program would crash at runtime there
    return jax.default_backend() == "cpu"


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """Repeat `body` while `cond(*loop_vars)` holds.

    Reference: control_flow.py:755. `loop_vars` is a non-empty list/tuple of
    Tensors (nests allowed); `body` must return the same structure with the
    same shapes/dtypes. Returns the final loop vars (list, matching reference).
    """
    if not isinstance(loop_vars, (list, tuple)) or len(loop_vars) == 0:
        raise TypeError("loop_vars must be a non-empty list or tuple")
    loop_vars = list(loop_vars)

    first_pred = cond(*loop_vars)
    if not _is_traced(first_pred, *jax.tree_util.tree_leaves(
            loop_vars, is_leaf=_is_tensor_leaf)):
        # eager: plain Python loop, tape autograd flows through body ops
        pred = first_pred
        while builtins.bool(_unwrap(pred)):
            out = body(*loop_vars)
            if not isinstance(out, (list, tuple)):
                out = [out]
            loop_vars = list(out)
            pred = cond(*loop_vars)
        return loop_vars

    init_arrays, treedef = _flatten(loop_vars)

    def cond_fn(arrays):
        with tape.no_grad():
            vars_ = _rebuild(arrays, treedef)
            return _scalar_bool(cond(*vars_))

    def body_fn(arrays):
        with tape.no_grad():
            vars_ = _rebuild(arrays, treedef)
            out = body(*vars_)
            if not isinstance(out, (list, tuple)):
                out = [out]
            out_arrays, out_treedef = _flatten(list(out))
            if out_treedef != treedef:
                raise ValueError(
                    "body output structure does not match loop_vars: "
                    f"{out_treedef} vs {treedef}")
            _check_dtypes(out_arrays, init_arrays, "while_loop body", "loop_vars")
            return out_arrays

    final = lax.while_loop(cond_fn, body_fn, init_arrays)
    return list(_rebuild(final, treedef))


def _run_branch(fn):
    out = fn() if fn is not None else None
    return out


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Run `true_fn()` if pred else `false_fn()`. Reference: control_flow.py:1637."""
    if not _is_traced(pred):
        if builtins.bool(_unwrap(pred)):
            return _run_branch(true_fn)
        return _run_branch(false_fn)

    # traced: each branch's ops are emitted ONLY inside its lax.cond branch
    # (so the unselected branch never executes at runtime); the output
    # structure/dtypes are probed up front with eval_shape, which traces
    # abstractly without adding ops to the outer program.
    with tape.no_grad():
        treedef, protos = _probe(true_fn if true_fn is not None else _none_fn)

        def t_fn(_):
            return _flatten(true_fn() if true_fn is not None else None)[0]

        def f_fn(_):
            out_arrays, out_treedef = _flatten(
                false_fn() if false_fn is not None else None)
            if out_treedef != treedef:
                raise ValueError(
                    "true_fn and false_fn must return the same structure: "
                    f"{treedef} vs {out_treedef}")
            _check_dtypes(out_arrays, protos, "false_fn", "true_fn")
            return out_arrays

        result = lax.cond(_scalar_bool(pred), t_fn, f_fn, None)
    return _rebuild(result, treedef)


def case(pred_fn_pairs, default=None, name=None):
    """First pair whose pred is True runs. Reference: control_flow.py:1062."""
    if not isinstance(pred_fn_pairs, (list, tuple)) or not pred_fn_pairs:
        raise TypeError("pred_fn_pairs must be a non-empty list or tuple")
    for pair in pred_fn_pairs:
        if not isinstance(pair, tuple) or len(pair) != 2 or not callable(pair[1]):
            raise TypeError("each element must be a (pred, callable) tuple")
    preds = [p for p, _ in pred_fn_pairs]
    fns = [f for _, f in pred_fn_pairs]
    if default is None:
        default = fns[-1]  # reference semantics: last fn doubles as default

    if not _is_traced(*preds):
        for p, f in zip(preds, fns):
            if builtins.bool(_unwrap(p)):
                return f()
        return default()

    # traced: index of first true pred, else len(preds) -> default branch
    stacked = jnp.stack([_scalar_bool(p) for p in preds])
    any_true = jnp.any(stacked)
    first = jnp.argmax(stacked)  # first True (argmax of bools)
    index = jnp.where(any_true, first, len(preds))
    return _switch_traced(index, fns + [default])


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Select a branch by integer index. Reference: control_flow.py:1185.

    `branch_fns` is a dict {int: fn}, a list of (int, fn), or a list of fns
    (implicitly enumerated).
    """
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif isinstance(branch_fns, (list, tuple)):
        if branch_fns and callable(branch_fns[0]):
            items = list(enumerate(branch_fns))
        else:
            items = sorted(((int(k), f) for k, f in branch_fns),
                           key=lambda kv: kv[0])
    else:
        raise TypeError("branch_fns must be a dict, list or tuple")
    if not items:
        raise TypeError("branch_fns must not be empty")
    keys = [k for k, _ in items]
    if len(set(keys)) != len(keys):
        raise ValueError(f"duplicate branch keys: {keys}")
    fns = [f for _, f in items]
    if default is None:
        default = fns[-1]

    if not _is_traced(branch_index):
        idx = builtins.int(_unwrap(branch_index))
        for k, f in items:
            if k == idx:
                return f()
        return default()

    idx = jnp.asarray(_unwrap(branch_index)).reshape(()).astype(jnp.int32)
    pos = jnp.full((), len(fns), jnp.int32)  # default slot
    for i, k in enumerate(keys):
        pos = jnp.where(idx == k, jnp.int32(i), pos)
    return _switch_traced(pos, fns + [default])


def _switch_traced(index, fns):
    """lax.switch over no-arg branch closures returning matching nests."""
    with tape.no_grad():
        treedef, protos = _probe(fns[0])

        def make(fn):
            def branch(_):
                out_arrays, out_treedef = _flatten(fn())
                if out_treedef != treedef:
                    raise ValueError(
                        "all branches must return the same structure: "
                        f"{treedef} vs {out_treedef}")
                _check_dtypes(out_arrays, protos, "branch", "branch 0")
                return out_arrays
            return branch

        index = jnp.clip(jnp.asarray(index).astype(jnp.int32), 0, len(fns) - 1)
        result = lax.switch(index, [make(f) for f in fns], None)
    return _rebuild(result, treedef)


def Assert(cond, data=None, summarize=20, name=None):
    """Assert a condition holds. Reference: control_flow.py:59.

    Eager: raises ValueError with the first `summarize` elements of each tensor
    in `data`. Traced: emits a debug print only when violated, on backends that
    support host callbacks (CPU); on the axon TPU plugin (no host send/recv) it
    is a no-op — FLAGS_check_nan_inf-style post-hoc checking is the
    compiled-mode diagnosis path there.
    """
    if not _is_traced(cond):
        if not builtins.bool(jnp.asarray(_unwrap(cond)).all()):
            parts = []
            for d in (data or []):
                v = jnp.asarray(_unwrap(d)).reshape(-1)[:summarize]
                parts.append(str(v))
            raise ValueError(
                f"Assert failed{': ' + ', '.join(parts) if parts else ''}")
        return None
    if _debug_callbacks_supported():
        ok = _scalar_bool(cond)
        msg = "Assert violated" + ("" if not name else f" ({name})")
        lax.cond(ok, lambda: None, lambda: jax.debug.print(msg))
    return None


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Print a tensor's value (works inside traced programs via jax.debug.print).

    Reference: control_flow.py:2215. Returns the input unchanged.
    """
    prefix = (message + " ") if message else ""
    v = _unwrap(input)
    if isinstance(jnp.asarray(v), jax.core.Tracer):
        if _debug_callbacks_supported():
            jax.debug.print(prefix + "{x}", x=v)
    else:
        arr = jnp.asarray(v).reshape(-1)[:summarize]
        print(f"{prefix}{arr}")
    return input
