"""Minimal paddle.static surface for the trace-and-compile world.

Reference: python/paddle/static/input.py (InputSpec) and the static.nn
namespace. The legacy Program/Executor machinery is absorbed by jax tracing
(SURVEY §7.1), but InputSpec survives as the shape/dtype declaration used by
``paddle.jit.save``'s program export — a None dim becomes a symbolic dimension
in the exported StableHLO (batch-polymorphic serving).
"""
from __future__ import annotations

import numpy as np


class InputSpec:
    """Reference: python/paddle/static/input.py:44."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = np.dtype(str(dtype).replace("paddle.", "")
                              if not isinstance(dtype, np.dtype) else dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, ndarray.dtype, name)

    def batch(self, batch_size):
        return InputSpec((batch_size,) + self.shape, self.dtype, self.name)

    def unbatch(self):
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


def _no_program(name):
    raise RuntimeError(
        f"paddle.static.{name}() has no equivalent here: there is no Program "
        "IR — models are traced (jaxpr/StableHLO) at call time. Use "
        "paddle.jit.to_static(layer) for a compiled callable, "
        "paddle.static.InputSpec for shape contracts, and "
        "paddle.jit.save/load for deployable artifacts.")


def default_main_program():
    """Reference: python/paddle/base/framework.py default_main_program. The
    Program abstraction is absorbed by tracing; raising (not returning None)
    keeps reference-style `prog.global_block()` code from dying two frames
    later with an opaque NoneType error (VERDICT r4 weak #8)."""
    _no_program("default_main_program")


def default_startup_program():
    _no_program("default_startup_program")


from . import nn  # noqa: E402,F401
from .nn.control_flow import Assert, Print  # noqa: E402,F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """Reference: python/paddle/static/io.py save_inference_model. The
    trace-and-compile world has no Program, so the deployable artifact is the
    jax.export bundle `paddle.jit.save` writes; this entry accepts either the
    reference calling convention with a Layer in place of fetch_vars, or
    (layer, input_spec) via kwargs.

    Usage: save_inference_model(prefix, input_specs, layer) where input_specs
    is a list of InputSpec and layer the model to export."""
    from .. import jit as _jit

    layer = kwargs.pop("layer", None)
    input_spec = kwargs.pop("input_spec", None)
    if layer is None and hasattr(fetch_vars, "state_dict"):
        layer, input_spec = fetch_vars, feed_vars
    if layer is None:
        raise TypeError(
            "save_inference_model needs the model Layer: pass it as "
            "fetch_vars (with InputSpecs as feed_vars) or layer=...")
    if input_spec is not None and not isinstance(input_spec, (list, tuple)):
        input_spec = [input_spec]
    _jit.save(layer, path_prefix, input_spec=input_spec, **kwargs)
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Reference: static/io.py load_inference_model, which returns
    [program, feed_names, fetch_targets]. Here the 'program' is the loaded
    callable (a TranslatedLayer-role object from paddle.jit.load); feed/fetch
    names come from its exported signature when available."""
    from .. import jit as _jit

    fn = _jit.load(path_prefix, **kwargs)
    feed_names = list(getattr(fn, "input_names", []) or [])
    fetch_targets = list(getattr(fn, "output_names", []) or [])
    return [fn, feed_names, fetch_targets]
