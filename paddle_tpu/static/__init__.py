"""Minimal paddle.static surface for the trace-and-compile world.

Reference: python/paddle/static/input.py (InputSpec) and the static.nn
namespace. The legacy Program/Executor machinery is absorbed by jax tracing
(SURVEY §7.1), but InputSpec survives as the shape/dtype declaration used by
``paddle.jit.save``'s program export — a None dim becomes a symbolic dimension
in the exported StableHLO (batch-polymorphic serving).
"""
from __future__ import annotations

import numpy as np


class InputSpec:
    """Reference: python/paddle/static/input.py:44."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = np.dtype(str(dtype).replace("paddle.", "")
                              if not isinstance(dtype, np.dtype) else dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, ndarray.dtype, name)

    def batch(self, batch_size):
        return InputSpec((batch_size,) + self.shape, self.dtype, self.name)

    def unbatch(self):
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


def _no_program(name):
    raise RuntimeError(
        f"paddle.static.{name}() has no equivalent here: there is no Program "
        "IR — models are traced (jaxpr/StableHLO) at call time. Use "
        "paddle.jit.to_static(layer) for a compiled callable, "
        "paddle.static.InputSpec for shape contracts, and "
        "paddle.jit.save/load for deployable artifacts.")


def default_main_program():
    """Reference: python/paddle/base/framework.py default_main_program. The
    Program abstraction is absorbed by tracing; raising (not returning None)
    keeps reference-style `prog.global_block()` code from dying two frames
    later with an opaque NoneType error (VERDICT r4 weak #8)."""
    _no_program("default_main_program")


def default_startup_program():
    _no_program("default_startup_program")


from . import nn  # noqa: E402,F401
from .nn.control_flow import Assert, Print  # noqa: E402,F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """Reference: python/paddle/static/io.py save_inference_model. The
    trace-and-compile world has no Program, so the deployable artifact is the
    jax.export bundle `paddle.jit.save` writes; this entry accepts either the
    reference calling convention with a Layer in place of fetch_vars, or
    (layer, input_spec) via kwargs.

    Usage: save_inference_model(prefix, input_specs, layer) where input_specs
    is a list of InputSpec and layer the model to export."""
    from .. import jit as _jit

    layer = kwargs.pop("layer", None)
    input_spec = kwargs.pop("input_spec", None)
    if layer is None and hasattr(fetch_vars, "state_dict"):
        layer, input_spec = fetch_vars, feed_vars
    if layer is None:
        raise TypeError(
            "save_inference_model needs the model Layer: pass it as "
            "fetch_vars (with InputSpecs as feed_vars) or layer=...")
    if input_spec is not None and not isinstance(input_spec, (list, tuple)):
        input_spec = [input_spec]
    _jit.save(layer, path_prefix, input_spec=input_spec, **kwargs)
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Reference: static/io.py load_inference_model, which returns
    [program, feed_names, fetch_targets]. Here the 'program' is the loaded
    callable (a TranslatedLayer-role object from paddle.jit.load); feed/fetch
    names come from its exported signature when available."""
    from .. import jit as _jit

    fn = _jit.load(path_prefix, **kwargs)
    feed_names = list(getattr(fn, "input_names", []) or [])
    fetch_targets = list(getattr(fn, "output_names", []) or [])
    return [fn, feed_names, fetch_targets]


# ------------------------------------------------------- round-5 parity tail
def _absorbed(name, hint):
    def fn(*a, **k):
        raise RuntimeError(
            f"paddle.static.{name} has no equivalent here: {hint}")

    fn.__name__ = name
    return fn


class _AbsorbedClass:
    """Program-era machinery absorbed by tracing: instantiation raises with a
    pointer at the supported path (same policy as default_main_program —
    VERDICT r4 weak #8: fail loudly and helpfully, never return None)."""

    _hint = "use paddle.jit.to_static / TrainStep (tracing replaces Programs)"

    def __init__(self, *a, **k):
        raise RuntimeError(
            f"paddle.static.{type(self).__name__} has no equivalent here: "
            f"{self._hint}")


class Program(_AbsorbedClass):
    pass


class CompiledProgram(_AbsorbedClass):
    pass


class Executor(_AbsorbedClass):
    _hint = ("there is no Program executor — call the jitted layer / "
             "TrainStep directly (one compiled XLA program per step)")


class Variable(_AbsorbedClass):
    _hint = "tensors are eager paddle.Tensor; shape contracts via InputSpec"


class BuildStrategy:
    """Reference: BuildStrategy — fusion/memory knobs for the legacy graph
    executor. XLA owns those decisions; attributes are accepted and recorded
    so reference scripts run, with no effect (documented no-op, like the
    inference Config knobs)."""

    def __setattr__(self, k, v):
        object.__setattr__(self, k, v)


class IpuStrategy(_AbsorbedClass):
    _hint = "no IPU backend exists in this build (PJRT is the device ABI)"


class IpuCompiledProgram(_AbsorbedClass):
    _hint = "no IPU backend exists in this build (PJRT is the device ABI)"


class ExponentialMovingAverage:
    """Reference: static/ema.py — EMA of trainable parameters with
    apply/restore. Works eagerly on Layer parameters (the dynamic-graph
    equivalent the rest of this build uses)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._ema = {}
        self._backup = None
        self._params = []

    def register(self, parameters):
        self._params = list(parameters)
        for p in self._params:
            self._ema[id(p)] = p._value

    def update(self):
        if not self._params:
            raise RuntimeError("call register(parameters) first")
        for p in self._params:
            prev = self._ema.get(id(p), p._value)
            self._ema[id(p)] = self._decay * prev + (1 - self._decay) * p._value

    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._value for p in self._params}
        for p in self._params:
            p._value = self._ema[id(p)].astype(p._value.dtype)

    def restore(self, executor=None):
        if self._backup:
            for p in self._params:
                p._value = self._backup[id(p)]
        self._backup = None


def data(name, shape, dtype="float32", lod_level=0):
    """Reference: static.data — declares a graph input; here it IS an
    InputSpec (the shape contract object to_static/jit.save consume)."""
    return InputSpec(shape, dtype, name)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Reference: static.create_parameter — a free-standing Parameter."""
    from ..nn.layer import Layer

    helper = Layer()
    return helper.create_parameter(shape, attr=attr, dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """Reference: static.create_global_var — a non-trainable global tensor."""
    import jax.numpy as jnp

    from ..tensor import Tensor

    t = Tensor(jnp.full(list(shape), value, dtype), stop_gradient=True)
    t.persistable = persistable
    return t


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Reference: static/nn/metric.py accuracy — top-k accuracy of a batch."""
    import jax.numpy as jnp

    from ..tensor import Tensor

    logits = input._value
    lab = label._value.reshape(-1)
    topk = jnp.argsort(-logits, axis=-1)[:, :k]
    hit = jnp.any(topk == lab[:, None], axis=1)
    return Tensor(jnp.mean(hit.astype(jnp.float32)))


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Reference: static/nn/metric.py auc — batch ROC-AUC (threshold-bucket
    approximation, same algorithm as metric.Auc)."""
    import numpy as np

    from ..metric import Auc
    from ..tensor import Tensor

    m = Auc(curve=curve, num_thresholds=num_thresholds)
    preds = np.asarray(input._value)
    if preds.ndim == 1:
        preds = np.stack([1 - preds, preds], axis=1)
    m.update(preds, np.asarray(label._value))
    import jax.numpy as jnp

    return Tensor(jnp.asarray(m.accumulate(), jnp.float32))


class name_scope:
    """Reference: static.name_scope — operator name prefix context; naming is
    cosmetic under tracing (jax op metadata carries source info), so this is
    a functional no-op context manager preserved for script parity."""

    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference: static.py_func — host-callback op. Eager world: just call
    it (jax.pure_callback is the traced analog, used by ops that need it)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    result = func(*xs)
    return result


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference: static.gradients — reverse-mode grads of targets wrt
    inputs; the tape provides it eagerly."""
    from ..autograd import tape

    ts = targets if isinstance(targets, (list, tuple)) else [targets]
    xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return tape.grad(ts, xs, allow_unused=True)


append_backward = _absorbed(
    "append_backward", "gradients come from loss.backward() / paddle.grad "
    "(tape autograd) — there is no Program to append ops to")


def cpu_places(device_count=None):
    import jax

    n = device_count or int(__import__("os").environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    return []  # no CUDA devices in a TPU build


def xpu_places(device_ids=None):
    return []


from ..device import CPUPlace  # noqa: E402


def global_scope():
    raise RuntimeError(
        "paddle.static.global_scope has no equivalent here: variables live "
        "on Layers/Tensors, not in a Scope — read layer.state_dict()")


def scope_guard(scope):
    raise RuntimeError(
        "paddle.static.scope_guard has no equivalent here (no Scope); "
        "state lives on Layer objects")


def program_guard(main_program, startup_program=None):
    raise RuntimeError(
        "paddle.static.program_guard has no equivalent here: build models as "
        "Layers and compile with paddle.jit.to_static")


def device_guard(device=None):
    raise RuntimeError(
        "paddle.static.device_guard has no equivalent here: placement is "
        "mesh/sharding-driven (paddle.distributed.shard_tensor)")


def ipu_shard_guard(index=-1, stage=-1):
    raise RuntimeError("no IPU backend exists in this build")


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise RuntimeError("no IPU backend exists in this build")


save = _absorbed(
    "save", "use paddle.save(layer.state_dict(), path) or paddle.jit.save")
load = _absorbed(
    "load", "use paddle.load + layer.set_state_dict, or paddle.jit.load")
save_to_file = _absorbed(
    "save_to_file", "artifacts are written by paddle.jit.save")
load_from_file = _absorbed(
    "load_from_file", "artifacts are read by paddle.jit.load")
serialize_program = _absorbed(
    "serialize_program", "the serialized program is the jax.export StableHLO "
    "bundle paddle.jit.save writes")
deserialize_program = _absorbed(
    "deserialize_program", "use paddle.jit.load on a jit.save bundle")
serialize_persistables = _absorbed(
    "serialize_persistables", "use paddle.save(layer.state_dict(), ...)")
deserialize_persistables = _absorbed(
    "deserialize_persistables", "use paddle.load + set_state_dict")
load_program_state = _absorbed(
    "load_program_state", "use paddle.load on a .pdparams state dict")
set_program_state = _absorbed(
    "set_program_state", "use layer.set_state_dict")
ctr_metric_bundle = _absorbed(
    "ctr_metric_bundle", "parameter-server CTR metrics are out of scope "
    "(SURVEY.md §9); use paddle.metric.Auc")


class WeightNormParamAttr:
    """Reference: static.WeightNormParamAttr — ParamAttr requesting weight
    normalization; here weight_norm is a Layer transform
    (paddle.nn.utils.weight_norm), this attr records the request."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


normalize_program = _absorbed(
    "normalize_program", "there is no Program to normalize — paddle.jit.save "
    "exports the pruned inference function directly from input_spec")
