"""Minimal paddle.static surface for the trace-and-compile world.

Reference: python/paddle/static/input.py (InputSpec) and the static.nn
namespace. The legacy Program/Executor machinery is absorbed by jax tracing
(SURVEY §7.1), but InputSpec survives as the shape/dtype declaration used by
``paddle.jit.save``'s program export — a None dim becomes a symbolic dimension
in the exported StableHLO (batch-polymorphic serving).
"""
from __future__ import annotations

import numpy as np


class InputSpec:
    """Reference: python/paddle/static/input.py:44."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = np.dtype(str(dtype).replace("paddle.", "")
                              if not isinstance(dtype, np.dtype) else dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, ndarray.dtype, name)

    def batch(self, batch_size):
        return InputSpec((batch_size,) + self.shape, self.dtype, self.name)

    def unbatch(self):
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


def default_main_program():  # compat no-op: jaxpr replaces Program
    return None


def default_startup_program():
    return None


from . import nn  # noqa: E402,F401
from .nn.control_flow import Assert, Print  # noqa: E402,F401
