"""paddle.text.datasets — parsers for the standard text corpora.

Reference: python/paddle/text/datasets/ (uci_housing.py, imdb.py, imikolov.py).
Zero-egress environment: ``download=True`` raises; parsers consume local files
in the upstream formats (tests synthesize them).
"""
from __future__ import annotations

import os
import re
import tarfile

import numpy as np

from ..io import Dataset

_NO_EGRESS = ("this build has no network egress: pass data_file pointing at an "
              "already-downloaded copy instead of download=True")

__all__ = ["UCIHousing", "Imdb", "Imikolov"]


class UCIHousing(Dataset):
    """Whitespace-separated 14-column housing data (reference uci_housing.py);
    features are normalized with the training-split statistics."""

    N_FEATURES = 13

    def __init__(self, data_file=None, mode="train", download=False):
        if data_file is None:
            if download:
                raise RuntimeError(_NO_EGRESS)
            raise ValueError(f"UCIHousing needs data_file ({_NO_EGRESS})")
        raw = np.loadtxt(data_file).astype("float32")
        if raw.ndim == 1:
            raw = raw.reshape(-1, self.N_FEATURES + 1)
        # reference ratio: 80/20 train/test split after global normalization
        feats, target = raw[:, :-1], raw[:, -1:]
        mx, mn, avg = feats.max(0), feats.min(0), feats.mean(0)
        feats = (feats - avg) / np.maximum(mx - mn, 1e-8)
        split = int(len(raw) * 0.8)
        if mode == "train":
            self.data = np.concatenate([feats[:split], target[:split]], 1)
        else:
            self.data = np.concatenate([feats[split:], target[split:]], 1)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment: aclImdb tar with {train,test}/{pos,neg}/*.txt members
    (reference imdb.py — same tar layout, same tokenizer regex)."""

    def __init__(self, data_file=None, mode="train", cutoff=150, download=False):
        if data_file is None:
            if download:
                raise RuntimeError(_NO_EGRESS)
            raise ValueError(f"Imdb needs data_file ({_NO_EGRESS})")
        # vocab is built over BOTH splits (reference imdb.py matches
        # aclImdb/((train)|(test))/...) so train/test indices are compatible
        vocab_pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        mode_pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        tokenizer = re.compile(r"\w+")
        docs, labels = [], []
        freq: dict[str, int] = {}
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                if not vocab_pat.match(member.name):
                    continue
                text = tf.extractfile(member).read().decode("utf-8", "ignore")
                words = [w.lower() for w in tokenizer.findall(text)]
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
                m = mode_pat.match(member.name)
                if m:
                    docs.append(words)
                    labels.append(0 if m.group(1) == "pos" else 1)
        # reference semantics: keep words with freq STRICTLY above cutoff
        vocab = [w for w, c in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
                 if c > cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.asarray([self.word_idx.get(w, unk) for w in d],
                                dtype=np.int64) for d in docs]
        self.labels = np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram dataset (reference imikolov.py): tar with
    ./simple-examples/data/ptb.{train,valid}.txt, returns n-grams."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=False):
        if data_file is None:
            if download:
                raise RuntimeError(_NO_EGRESS)
            raise ValueError(f"Imikolov needs data_file ({_NO_EGRESS})")
        split = "train" if mode == "train" else "valid"
        lines = None
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                if member.name.endswith(f"ptb.{split}.txt"):
                    data = tf.extractfile(member).read().decode()
                    lines = [l.strip().split() for l in data.splitlines() if l.strip()]
        if lines is None:
            raise ValueError(
                f"{data_file!r} has no ptb.{split}.txt member — wrong archive?")
        freq: dict[str, int] = {}
        for words in lines:
            for w in words:
                freq[w] = freq.get(w, 0) + 1
        vocab = [w for w, c in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
                 if c >= min_word_freq and w != "<unk>"]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data = []
        for words in lines:
            ids = ([self.word_idx.get("<s>", unk)]
                   + [self.word_idx.get(w, unk) for w in words]
                   + [self.word_idx.get("<e>", unk)])
            if data_type.upper() == "NGRAM":
                for i in range(len(ids) - window_size + 1):
                    self.data.append(np.asarray(ids[i:i + window_size],
                                                dtype=np.int64))
            else:  # SEQ
                self.data.append(np.asarray(ids, dtype=np.int64))

    def __getitem__(self, idx):
        return tuple(self.data[idx])

    def __len__(self):
        return len(self.data)
