"""paddle.text.datasets — parsers for the standard text corpora.

Reference: python/paddle/text/datasets/ (uci_housing.py, imdb.py, imikolov.py).
Zero-egress environment: ``download=True`` raises; parsers consume local files
in the upstream formats (tests synthesize them).
"""
from __future__ import annotations

import os
import re
import tarfile

import numpy as np

from ..io import Dataset

_NO_EGRESS = ("this build has no network egress: pass data_file pointing at an "
              "already-downloaded copy instead of download=True")

__all__ = ["UCIHousing", "Imdb", "Imikolov"]


class UCIHousing(Dataset):
    """Whitespace-separated 14-column housing data (reference uci_housing.py);
    features are normalized with the training-split statistics."""

    N_FEATURES = 13

    def __init__(self, data_file=None, mode="train", download=False):
        if data_file is None:
            if download:
                raise RuntimeError(_NO_EGRESS)
            raise ValueError(f"UCIHousing needs data_file ({_NO_EGRESS})")
        raw = np.loadtxt(data_file).astype("float32")
        if raw.ndim == 1:
            raw = raw.reshape(-1, self.N_FEATURES + 1)
        # reference ratio: 80/20 train/test split after global normalization
        feats, target = raw[:, :-1], raw[:, -1:]
        mx, mn, avg = feats.max(0), feats.min(0), feats.mean(0)
        feats = (feats - avg) / np.maximum(mx - mn, 1e-8)
        split = int(len(raw) * 0.8)
        if mode == "train":
            self.data = np.concatenate([feats[:split], target[:split]], 1)
        else:
            self.data = np.concatenate([feats[split:], target[split:]], 1)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment: aclImdb tar with {train,test}/{pos,neg}/*.txt members
    (reference imdb.py — same tar layout, same tokenizer regex)."""

    def __init__(self, data_file=None, mode="train", cutoff=150, download=False):
        if data_file is None:
            if download:
                raise RuntimeError(_NO_EGRESS)
            raise ValueError(f"Imdb needs data_file ({_NO_EGRESS})")
        # vocab is built over BOTH splits (reference imdb.py matches
        # aclImdb/((train)|(test))/...) so train/test indices are compatible
        vocab_pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        mode_pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        tokenizer = re.compile(r"\w+")
        docs, labels = [], []
        freq: dict[str, int] = {}
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                if not vocab_pat.match(member.name):
                    continue
                text = tf.extractfile(member).read().decode("utf-8", "ignore")
                words = [w.lower() for w in tokenizer.findall(text)]
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
                m = mode_pat.match(member.name)
                if m:
                    docs.append(words)
                    labels.append(0 if m.group(1) == "pos" else 1)
        # reference semantics: keep words with freq STRICTLY above cutoff
        vocab = [w for w, c in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
                 if c > cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.asarray([self.word_idx.get(w, unk) for w in d],
                                dtype=np.int64) for d in docs]
        self.labels = np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram dataset (reference imikolov.py): tar with
    ./simple-examples/data/ptb.{train,valid}.txt, returns n-grams."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=False):
        if data_file is None:
            if download:
                raise RuntimeError(_NO_EGRESS)
            raise ValueError(f"Imikolov needs data_file ({_NO_EGRESS})")
        split = "train" if mode == "train" else "valid"
        lines = None
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                if member.name.endswith(f"ptb.{split}.txt"):
                    data = tf.extractfile(member).read().decode()
                    lines = [l.strip().split() for l in data.splitlines() if l.strip()]
        if lines is None:
            raise ValueError(
                f"{data_file!r} has no ptb.{split}.txt member — wrong archive?")
        freq: dict[str, int] = {}
        for words in lines:
            for w in words:
                freq[w] = freq.get(w, 0) + 1
        vocab = [w for w, c in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
                 if c >= min_word_freq and w != "<unk>"]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data = []
        for words in lines:
            ids = ([self.word_idx.get("<s>", unk)]
                   + [self.word_idx.get(w, unk) for w in words]
                   + [self.word_idx.get("<e>", unk)])
            if data_type.upper() == "NGRAM":
                for i in range(len(ids) - window_size + 1):
                    self.data.append(np.asarray(ids[i:i + window_size],
                                                dtype=np.int64))
            else:  # SEQ
                self.data.append(np.asarray(ids, dtype=np.int64))

    def __getitem__(self, idx):
        return tuple(self.data[idx])

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """CoNLL-2005 SRL (reference conll05.py). Members expected in the tar:
    the test.wsj words/props files plus the word/verb/target dicts. Yields
    (word_ids, ctx_n2/n1/0/p1/p2, mark, label_ids) per prop, following the
    reference's feature construction."""

    def __init__(self, data_file=None, word_dict_file=None, verb_dict_file=None,
                 target_dict_file=None, mode="test", download=False):
        for f, n in ((data_file, "data_file"), (word_dict_file, "word_dict_file"),
                     (verb_dict_file, "verb_dict_file"),
                     (target_dict_file, "target_dict_file")):
            if f is None:
                if download:
                    raise RuntimeError(_NO_EGRESS)
                raise ValueError(f"Conll05st needs {n} ({_NO_EGRESS})")
        self.word_dict = self._load_dict(word_dict_file)
        self.verb_dict = self._load_dict(verb_dict_file)
        self.label_dict = self._load_dict(target_dict_file)
        self.data = self._load(data_file)

    @staticmethod
    def _load_dict(path):
        d = {}
        with open(path, "rb") as f:
            for i, line in enumerate(f.read().decode("utf-8").splitlines()):
                d[line.strip()] = i
        return d

    def _load(self, data_file):
        # words file: one token per line, sentences separated by blank lines;
        # props file: predicate + per-token SRL tags aligned to the sentence
        sents, props = [], []
        with tarfile.open(data_file, "r:*") as tf:
            words_m = [m for m in tf.getmembers() if m.name.endswith("words")]
            props_m = [m for m in tf.getmembers() if m.name.endswith("props")]
            if not words_m or not props_m:
                raise ValueError("archive lacks .words/.props members")
            words_txt = tf.extractfile(words_m[0]).read().decode("utf-8")
            props_txt = tf.extractfile(props_m[0]).read().decode("utf-8")
        cur_w: list = []
        for line in words_txt.splitlines():
            if line.strip():
                cur_w.append(line.strip())
            elif cur_w:
                sents.append(cur_w)
                cur_w = []
        if cur_w:
            sents.append(cur_w)
        cur_p: list = []
        for line in props_txt.splitlines():
            if line.strip():
                cur_p.append(line.split())
            elif cur_p:
                props.append(cur_p)
                cur_p = []
        if cur_p:
            props.append(cur_p)
        unk = self.word_dict.get("<unk>", 0)
        data = []
        for sent, prop in zip(sents, props):
            n = len(sent)
            preds = [i for i, row in enumerate(prop) if row and row[0] != "-"]
            for col, pi in enumerate(preds):
                verb = sent[pi]
                labels = []
                for row in prop:
                    tag = row[col + 1] if len(row) > col + 1 else "O"
                    labels.append(self.label_dict.get(tag, 0))
                wids = [self.word_dict.get(w.lower(), unk) for w in sent]
                ctx = [self.word_dict.get(
                    sent[min(max(pi + off, 0), n - 1)].lower(), unk)
                    for off in (-2, -1, 0, 1, 2)]
                mark = [1 if i == pi else 0 for i in range(n)]
                data.append((np.asarray(wids, np.int64),
                             *(np.asarray([c] * n, np.int64) for c in ctx),
                             np.asarray(mark, np.int64),
                             np.asarray(labels, np.int64)))
        return data

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """MovieLens-1M (reference movielens.py): ml-1m.zip with ratings.dat /
    users.dat / movies.dat ('::'-separated). Yields (user_id, gender, age,
    occupation, movie_id, category_ids, title_ids, rating)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=False):
        if data_file is None:
            if download:
                raise RuntimeError(_NO_EGRESS)
            raise ValueError(f"Movielens needs data_file ({_NO_EGRESS})")
        import zipfile

        with zipfile.ZipFile(data_file) as zf:
            def read(name):
                cand = [n for n in zf.namelist() if n.endswith(name)]
                return zf.read(cand[0]).decode("latin1").splitlines()

            movies = {}
            cats: dict[str, int] = {}
            titles: dict[str, int] = {}
            for line in read("movies.dat"):
                mid, title, genres = line.split("::")
                gids = []
                for g in genres.split("|"):
                    gids.append(cats.setdefault(g, len(cats)))
                tids = []
                for w in title.split():
                    tids.append(titles.setdefault(w.lower(), len(titles)))
                movies[int(mid)] = (gids, tids)
            users = {}
            for line in read("users.dat"):
                uid, gender, age, occ, _zip = line.split("::")
                users[int(uid)] = (0 if gender == "M" else 1, int(age), int(occ))
            rng = np.random.RandomState(rand_seed)
            self.data = []
            for line in read("ratings.dat"):
                uid, mid, rating, _ts = line.split("::")
                uid, mid = int(uid), int(mid)
                if mid not in movies or uid not in users:
                    continue
                is_test = rng.rand() < test_ratio
                if (mode == "test") != is_test:
                    continue
                g, a, o = users[uid]
                gids, tids = movies[mid]
                self.data.append((
                    np.int64(uid), np.int64(g), np.int64(a), np.int64(o),
                    np.int64(mid), np.asarray(gids, np.int64),
                    np.asarray(tids, np.int64), np.float32(float(rating))))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class _WMT(Dataset):
    """Shared WMT14/WMT16 en-de machinery (reference wmt14.py/wmt16.py):
    tarball with src/trg dict files + parallel corpus; yields
    (src_ids, trg_ids[:-1], trg_ids[1:]) with <s>/<e>/<unk> conventions."""

    BOS, EOS, UNK = "<s>", "<e>", "<unk>"

    def __init__(self, data_file, mode, src_suffix, trg_suffix,
                 src_dict_size=-1, trg_dict_size=-1):
        self.src_dict: dict = {}
        self.trg_dict: dict = {}
        self.data = []
        with tarfile.open(data_file, "r:*") as tf:
            names = tf.getnames()

            def pick(sub):
                c = [n for n in names if sub in n]
                if not c:
                    raise ValueError(f"archive lacks a '{sub}' member")
                return tf.extractfile(c[0]).read().decode("utf-8",
                                                          "ignore").splitlines()

            src_lines = pick(f"{mode}{src_suffix}")
            trg_lines = pick(f"{mode}{trg_suffix}")
        for lines, d, cap in ((src_lines, self.src_dict, src_dict_size),
                              (trg_lines, self.trg_dict, trg_dict_size)):
            for tok in (self.BOS, self.EOS, self.UNK):
                d.setdefault(tok, len(d))
            for line in lines:
                for w in line.split():
                    if cap < 0 or len(d) < cap:
                        d.setdefault(w, len(d))
        unk_s, unk_t = self.src_dict[self.UNK], self.trg_dict[self.UNK]
        for s, t in zip(src_lines, trg_lines):
            sid = [self.src_dict.get(w, unk_s) for w in s.split()]
            tid = ([self.trg_dict[self.BOS]]
                   + [self.trg_dict.get(w, unk_t) for w in t.split()]
                   + [self.trg_dict[self.EOS]])
            if sid and len(tid) > 2:
                self.data.append((np.asarray(sid, np.int64),
                                  np.asarray(tid[:-1], np.int64),
                                  np.asarray(tid[1:], np.int64)))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)

    def get_dict(self, lang="en", reverse=False):
        d = self.src_dict if lang == "en" else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else dict(d)


class WMT14(_WMT):
    """Reference wmt14.py — members named like train/train.en, train/train.de."""

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=False):
        if data_file is None:
            if download:
                raise RuntimeError(_NO_EGRESS)
            raise ValueError(f"WMT14 needs data_file ({_NO_EGRESS})")
        super().__init__(data_file, mode, ".en", ".de", dict_size, dict_size)


class WMT16(_WMT):
    """Reference wmt16.py — same layout, newstest-based splits."""

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=False):
        if data_file is None:
            if download:
                raise RuntimeError(_NO_EGRESS)
            raise ValueError(f"WMT16 needs data_file ({_NO_EGRESS})")
        super().__init__(data_file, mode, f".{lang}",
                         ".de" if lang == "en" else ".en",
                         src_dict_size, trg_dict_size)
