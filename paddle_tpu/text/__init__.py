"""paddle.text surface. Reference: python/paddle/text/__init__.py."""
from . import datasets  # noqa: F401
from .datasets import (  # noqa: F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)
from .viterbi_decode import ViterbiDecoder, viterbi_decode  # noqa: F401
