"""paddle.text surface. Reference: python/paddle/text/__init__.py."""
from . import datasets  # noqa: F401
from .datasets import Imdb, Imikolov, UCIHousing  # noqa: F401
from .viterbi_decode import ViterbiDecoder, viterbi_decode  # noqa: F401
