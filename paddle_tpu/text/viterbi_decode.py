"""Viterbi decoding. Reference: python/paddle/text/viterbi_decode.py:31.

TPU-native: the time recursion is a lax.scan over the sequence axis (static
trip count, no Python loop under jit); backtracking is a reverse scan over the
recorded argmax pointers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..nn.layer import Layer
from ..tensor import Tensor


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """potentials [B, T, N], transitions [N, N], lengths [B] →
    (scores [B], paths [B, T])."""
    pot = potentials._value if isinstance(potentials, Tensor) else jnp.asarray(potentials)
    trans = (transition_params._value if isinstance(transition_params, Tensor)
             else jnp.asarray(transition_params)).astype(pot.dtype)
    lens = (lengths._value if isinstance(lengths, Tensor)
            else jnp.asarray(lengths)).astype(jnp.int32)
    B, T, N = pot.shape

    if include_bos_eos_tag:
        # last tag = BOS, second-to-last = EOS (reference convention)
        bos, eos = N - 1, N - 2
        alpha0 = pot[:, 0] + trans[bos][None, :]
    else:
        alpha0 = pot[:, 0]

    def step(carry, t):
        alpha, history_dummy = carry
        # scores[b, i, j] = alpha[b, i] + trans[i, j] + pot[b, t, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)                 # [B, N]
        best_score = jnp.max(scores, axis=1) + pot[:, t]
        # sequences shorter than t keep their previous alpha (masked update)
        active = (t < lens)[:, None]
        new_alpha = jnp.where(active, best_score, alpha)
        ptr = jnp.where(active, best_prev, jnp.arange(N)[None, :])
        return (new_alpha, history_dummy), ptr

    (alpha, _), ptrs = jax.lax.scan(
        step, (alpha0, jnp.zeros((), jnp.int32)), jnp.arange(1, T))
    # ptrs: [T-1, B, N]

    if include_bos_eos_tag:
        alpha = alpha + trans[:, eos][None, :]

    last_tag = jnp.argmax(alpha, axis=1)                       # [B]
    scores = jnp.max(alpha, axis=1)

    def back(carry, t):
        tag = carry
        ptr_t = ptrs[t]                                        # [B, N]
        prev = jnp.take_along_axis(ptr_t, tag[:, None], axis=1)[:, 0]
        # positions beyond a sequence's length keep the same tag
        prev = jnp.where(t + 1 < lens, prev, tag)
        return prev, prev

    _, rev_path = jax.lax.scan(back, last_tag, jnp.arange(T - 2, -1, -1))
    path = jnp.concatenate(
        [jnp.flip(rev_path, 0), last_tag[None, :]], axis=0).T  # [B, T]
    return Tensor(scores), Tensor(path.astype(jnp.int64))


class ViterbiDecoder(Layer):
    """Reference viterbi_decode.py:110."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
