"""PyLayer: user-defined forward/backward. Reference: python/paddle/autograd/py_layer.py.

The reference uses PyLayer pervasively in distributed code (ScatterOp/GatherOp etc.). Here
a PyLayer's backward is spliced into the tape as a custom Node whose "vjp" calls the
user's backward with wrapped Tensors.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor
from . import tape


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self._unpack = None
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        from . import saved_tensors_hooks as _sth

        hooks = _sth._active
        if hooks is not None:
            self._saved = [hooks[0](t) for t in tensors]
            self._unpack = hooks[1]
        else:
            self._saved = list(tensors)
            self._unpack = None

    def saved_tensor(self):
        if self._unpack is not None:
            return [self._unpack(t) for t in self._saved]
        return list(self._saved)

    # paddle alias
    saved_tensors = property(lambda self: self.saved_tensor())


class PyLayerMeta(type):
    def __init__(cls, name, bases, attrs):
        super().__init__(name, bases, attrs)


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with tape.no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outputs, (tuple, list))
        out_list = [outputs] if single else list(outputs)
        out_tensors = [o for o in out_list if isinstance(o, Tensor)]

        diff_inputs = [
            a for a in args if isinstance(a, Tensor) and not a.stop_gradient
        ]
        if tape.is_grad_enabled() and diff_inputs:

            def vjp_fn(cotangents):
                grads_in = [
                    Tensor(c, stop_gradient=True) if c is not None else None
                    for c in cotangents
                ]
                with tape.no_grad():
                    result = cls.backward(ctx, *grads_in)
                if not isinstance(result, (tuple, list)):
                    result = (result,)
                # map returned grads (one per differentiable tensor input, paddle contract
                # is one per tensor input in order) onto diff_inputs
                flat = [r._value if isinstance(r, Tensor) else r for r in result]
                # If the user returned grads for all tensor args, filter to diff ones.
                tensor_args = [a for a in args if isinstance(a, Tensor)]
                if len(flat) == len(tensor_args) != len(diff_inputs):
                    flat = [
                        g for a, g in zip(tensor_args, flat) if not a.stop_gradient
                    ]
                return tuple(flat)

            tape.record(vjp_fn, diff_inputs, out_tensors, name=cls.__name__)
        return outputs
