"""Define-by-run autograd as a tape over `jax.vjp`.

Reference parity: the eager GradNode graph + `egr::Backward()` engine
(paddle/fluid/eager/grad_node_info.h, backward.h:26 in the reference). TPU-native design:
instead of per-op hand-written grad kernels, every recorded op captures a `jax.vjp` closure
— forward AND the pullback are built in one pass, both are jax-traceable, so the same tape
works eagerly on device and under `jit` tracing (where the residuals are tracers and the
whole backward fuses into the compiled program).

The tape is implicit: each produced Tensor holds a reference to the Node that made it;
`backward(root)` runs a topological sweep with per-node pending-dependency counts, exactly
the queue discipline of the reference's Backward() engine.
"""
from __future__ import annotations

import contextlib
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

# ---------------------------------------------------------------------------- grad mode

_grad_enabled = True


def is_grad_enabled() -> bool:
    return _grad_enabled


def set_grad_enabled(mode: bool):
    global _grad_enabled
    _grad_enabled = bool(mode)


class no_grad(contextlib.ContextDecorator):
    """paddle.no_grad — usable as context manager and decorator."""

    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = False
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = True
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False


@contextlib.contextmanager
def set_grad_enabled_ctx(mode: bool):
    global _grad_enabled
    prev = _grad_enabled
    _grad_enabled = bool(mode)
    try:
        yield
    finally:
        _grad_enabled = prev


# ---------------------------------------------------------------------------- tape nodes


class Node:
    """One recorded op: inputs (diff positions only), a vjp closure, #outputs."""

    __slots__ = (
        "vjp_fn",
        "inputs",
        "n_outputs",
        "name",
        "out_grads",
        "out_avals",
        "pending",
        "_hooks",
    )

    def __init__(
        self,
        vjp_fn: Callable,
        inputs: Sequence[Any],
        n_outputs: int,
        name: str,
        out_avals: Sequence[Any] | None = None,
    ):
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)  # Tensors (the differentiable inputs, in vjp order)
        self.n_outputs = n_outputs
        self.name = name
        self.out_grads: list[Any] = [None] * n_outputs
        self.out_avals = list(out_avals) if out_avals is not None else [None] * n_outputs
        self.pending = 0  # filled during backward topo pass
        self._hooks: list[Callable] | None = None

    def add_hook(self, hook: Callable):
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)

    def release(self):
        """Drop residuals so memory is freed once the node has run."""
        self.vjp_fn = None
        self.out_grads = [None] * self.n_outputs


def record(vjp_fn, input_tensors, outputs, name="op"):
    """Attach a Node to each output tensor. `outputs` is a list of Tensors."""
    node = Node(
        vjp_fn,
        input_tensors,
        len(outputs),
        name,
        out_avals=[(o.value.shape, o.value.dtype) for o in outputs],
    )
    for i, out in enumerate(outputs):
        out._grad_node = node
        out._grad_index = i
        out.stop_gradient = False
    return node


# ---------------------------------------------------------------------------- backward


def _accumulate(a, b):
    if a is None:
        return b
    return a + b


def _zero_cotangent(aval):
    """Zero cotangent for an unused output. Integer/bool outputs (argmax indices, masks)
    take jax's float0 tangent type, matching jax.vjp's contract."""
    shape, dtype = aval
    if jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(dtype, jnp.complexfloating):
        return jnp.zeros(shape, dtype)
    import numpy as _np

    return _np.zeros(shape, jax.dtypes.float0)


def backward(tensors, grad_tensors=None, retain_graph=False, capture=None):
    """paddle.autograd.backward / Tensor.backward.

    Topological sweep: count in-degrees (how many downstream nodes feed each node's
    outputs), then process nodes whose output grads are fully accumulated — mirroring the
    reference's queue-based engine (paddle/fluid/eager/backward.cc).

    `capture`: optional dict {id(tensor): None} — gradients flowing INTO these tensors
    (leaf or intermediate) are also accumulated into the dict; used by paddle.grad to
    harvest grads w.r.t. non-leaf tensors without touching .grad.
    """
    from ..tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # Seed gradients.
    roots = []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            continue
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; tensor has "
                    f"shape {t.shape}"
                )
            seed_val = jnp.ones_like(t.value)
        else:
            seed_val = g.value if isinstance(g, Tensor) else jnp.asarray(g)
        roots.append((t, seed_val))

    # Discover the reachable graph; count pending outputs per node.
    nodes: dict[int, Node] = {}
    order: list[Node] = []

    def visit(node: Node):
        if node is None or id(node) in nodes:
            return
        nodes[id(node)] = node
        node.pending = 0
        for inp in node.inputs:
            visit(inp._grad_node)
        order.append(node)

    for t, _ in roots:
        visit(t._grad_node)

    # pending = number of downstream consumers (nodes that will contribute grads to me).
    consumers: dict[int, int] = {id(n): 0 for n in order}
    for n in order:
        for inp in n.inputs:
            gn = inp._grad_node
            if gn is not None:
                consumers[id(gn)] += 1

    # Seed root node output grads / leaf grads.
    ready: list[Node] = []
    for t, seed_val in roots:
        if capture is not None and id(t) in capture:
            capture[id(t)] = _accumulate(capture[id(t)], seed_val)
        node = t._grad_node
        if node is None:
            if capture is None or id(t) not in capture:
                t._accumulate_grad(seed_val)
            continue
        idx = t._grad_index
        node.out_grads[idx] = _accumulate(node.out_grads[idx], seed_val)

    done: set[int] = set()

    def maybe_ready(n: Node):
        if id(n) in done:
            return
        if consumers[id(n)] == 0:
            ready.append(n)
            done.add(id(n))

    for n in order:
        maybe_ready(n)

    processed = 0
    while ready:
        node = ready.pop()
        processed += 1
        cotangents = tuple(
            g if g is not None else _zero_cotangent(aval)
            for g, aval in zip(node.out_grads, node.out_avals)
        )
        # jax.vjp closures take the output cotangent structure: single value if one
        # output, tuple otherwise (we always recorded the fn returning a tuple).
        if node.vjp_fn is None:
            raise RuntimeError(
                "Trying to backward through the graph a second time, but the saved "
                "intermediate results have already been freed. Specify retain_graph=True."
            )
        in_grads = node.vjp_fn(cotangents)
        if node._hooks:
            in_grads = list(in_grads)
            for hook in node._hooks:
                in_grads = [hook(g) if g is not None else None for g in in_grads]
        for inp, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            if capture is not None and id(inp) in capture:
                capture[id(inp)] = _accumulate(capture[id(inp)], g)
            gn = inp._grad_node
            if gn is None:
                # leaf (or detached intermediate): accumulate into .grad
                if not inp.stop_gradient and (capture is None or id(inp) not in capture):
                    inp._accumulate_grad(g)
            else:
                gn.out_grads[inp._grad_index] = _accumulate(
                    gn.out_grads[inp._grad_index], g
                )
                consumers[id(gn)] -= 1
                maybe_ready(gn)
        if not retain_graph:
            node.release()
        else:
            node.out_grads = [None] * node.n_outputs


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    allow_unused=False,
):
    """paddle.grad — functional gradient w.r.t. `inputs` (leaf OR intermediate tensors)
    without touching .grad fields. Grads are harvested via the backward sweep's capture
    dict, so non-leaf inputs receive the cotangent flowing into them."""
    from ..tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]

    prev_sg = [t.stop_gradient for t in inputs]
    for t in inputs:
        t.stop_gradient = False
    capture = {id(t): None for t in inputs}
    try:
        backward(outputs, grad_tensors=grad_outputs,
                 retain_graph=bool(retain_graph) or create_graph, capture=capture)
        results = []
        for t in inputs:
            g = capture[id(t)]
            if g is None:
                if not allow_unused:
                    raise RuntimeError(
                        f"gradient of input {t.name} is None (not reachable from "
                        "outputs); pass allow_unused=True to return None instead"
                    )
                results.append(None)
            else:
                results.append(Tensor(g, stop_gradient=not create_graph))
        return results
    finally:
        for t, sg in zip(inputs, prev_sg):
            t.stop_gradient = sg
