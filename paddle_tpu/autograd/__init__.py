"""Autograd public API. Reference: python/paddle/autograd/."""
from . import tape  # noqa: F401
from .tape import (  # noqa: F401
    backward,
    enable_grad,
    grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
)

def __getattr__(name):
    # PyLayer imports Tensor which imports this package: resolve lazily.
    if name in ("PyLayer", "PyLayerContext"):
        from . import py_layer

        return getattr(py_layer, name)
    raise AttributeError(name)


__all__ = [
    "backward",
    "grad",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "PyLayer",
    "PyLayerContext",
]
