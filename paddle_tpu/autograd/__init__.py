"""Autograd public API. Reference: python/paddle/autograd/."""
from . import tape  # noqa: F401
from .tape import (  # noqa: F401
    backward,
    enable_grad,
    grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
)

def __getattr__(name):
    # PyLayer imports Tensor which imports this package: resolve lazily.
    if name in ("PyLayer", "PyLayerContext"):
        from . import py_layer

        return getattr(py_layer, name)
    raise AttributeError(name)


__all__ = [
    "backward",
    "grad",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "PyLayer",
    "PyLayerContext",
]


def jacobian(ys, xs, batch_axis=None):
    """Reference: python/paddle/autograd/autograd.py jacobian — here eager
    and materialized (TPU-native: one jax.jacobian trace-and-compile instead
    of the reference's lazy row-by-row evaluation).

    Accepts either (func, x) — the functional form — or (y, x) where y was
    computed from x under the tape (uses the tape's vjp closure)."""
    import jax
    import jax.numpy as jnp

    from ..tensor import Tensor

    if callable(ys):
        fn = ys
        xs_t = xs if isinstance(xs, (list, tuple)) else (xs,)

        def raw(*vals):
            out = fn(*[Tensor(v) for v in vals])
            return out._value if isinstance(out, Tensor) else out

        jac = jax.jacobian(raw, argnums=tuple(range(len(xs_t))))(
            *[t._value for t in xs_t])
        if not isinstance(xs, (list, tuple)):
            return Tensor(jnp.asarray(jac[0]))
        return [Tensor(jnp.asarray(j)) for j in jac]
    # tensor form: the FULL Jacobian [ys.size, xs.size-shaped] via one VJP per
    # output element through the recorded tape (retain_graph across rows)
    import jax.numpy as _jnp

    from . import tape as _tape
    from ..tensor import Tensor as _T

    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    y_flat = ys.reshape([-1])
    m = y_flat.shape[0]
    rows_per_x = [[] for _ in xs_list]
    for i in range(m):
        cot = _jnp.zeros((m,), y_flat._value.dtype).at[i].set(1.0)
        gs = _tape.grad([y_flat], xs_list, grad_outputs=[_T(cot)],
                        retain_graph=True, allow_unused=True)
        for j, (slot, g) in enumerate(zip(rows_per_x, gs)):
            slot.append(_jnp.zeros(xs_list[j]._value.shape)
                        if g is None else g._value)
    outs = [
        _T(_jnp.stack([r.reshape(-1) for r in rows]).reshape(
            tuple(ys.shape) + tuple(x.shape)))
        for rows, x in zip(rows_per_x, xs_list)
    ]
    return outs if isinstance(xs, (list, tuple)) else outs[0]


def hessian(func, xs, batch_axis=None):
    """Reference: autograd.py hessian (functional form)."""
    import jax
    import jax.numpy as jnp

    from ..tensor import Tensor

    xs_t = xs if isinstance(xs, (list, tuple)) else (xs,)

    def raw(*vals):
        out = func(*[Tensor(v) for v in vals])
        return (out._value if isinstance(out, Tensor) else out).sum()

    h = jax.hessian(raw, argnums=tuple(range(len(xs_t))))(
        *[t._value for t in xs_t])
    if not isinstance(xs, (list, tuple)):
        return Tensor(jnp.asarray(h[0][0]))
    return [[Tensor(jnp.asarray(c)) for c in row] for row in h]


class saved_tensors_hooks:
    """Reference: autograd/saved_tensors_hooks.py — pack/unpack hooks for
    tensors saved by PyLayer.save_for_backward. Residuals captured inside
    compiled vjp closures are jax-internal and not interceptable; the hook
    surface covers the PyLayer path (the reference's documented use case)."""

    _active = None

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        saved_tensors_hooks._active = (self.pack_hook, self.unpack_hook)
        return self

    def __exit__(self, *exc):
        saved_tensors_hooks._active = None
        return False
