"""paddle.vision.datasets — dataset parsers for the standard vision corpora.

Reference: python/paddle/vision/datasets/ (MNIST idx-format parser mnist.py:190,
CIFAR tar-of-pickles cifar.py, folder.py DatasetFolder/ImageFolder). This
environment has zero network egress, so ``download=True`` raises with
instructions; all parsers consume local files in the exact upstream formats
(tests synthesize them). Decoding is numpy-only — no PIL dependency; the
'cv2'/'pil' backend knobs map to numpy HWC arrays.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder"]

_NO_EGRESS = ("this build has no network egress: pass image_path/label_path "
              "(or data_file) pointing at already-downloaded files instead of "
              "download=True")


def _maybe_open(path):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


class MNIST(Dataset):
    """idx-format parser (reference mnist.py:190 _parse_dataset).

    ``image_path``/``label_path``: local idx3-ubyte / idx1-ubyte files
    (optionally .gz). mode: 'train' | 'test' (used only for default names).
    """

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if image_path is None or label_path is None:
            if download:
                raise RuntimeError(_NO_EGRESS)
            raise ValueError("MNIST needs image_path and label_path "
                             f"({_NO_EGRESS})")
        self.mode = mode
        self.transform = transform
        self.images = self._parse_images(image_path)
        self.labels = self._parse_labels(label_path)
        assert len(self.images) == len(self.labels), "image/label count mismatch"

    @staticmethod
    def _parse_images(path):
        with _maybe_open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad idx3 magic {magic:#x} in {path}")
            buf = f.read(n * rows * cols)
        return np.frombuffer(buf, dtype=np.uint8).reshape(n, rows, cols)

    @staticmethod
    def _parse_labels(path):
        with _maybe_open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad idx1 magic {magic:#x} in {path}")
            buf = f.read(n)
        return np.frombuffer(buf, dtype=np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32")[..., None]  # HWC
        label = np.array([self.labels[idx]], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """CIFAR tar-of-pickled-batches parser (reference cifar.py).

    ``data_file``: local cifar-10-python.tar.gz (or an uncompressed .tar).
    """

    _META = {"batches": ["data_batch_1", "data_batch_2", "data_batch_3",
                         "data_batch_4", "data_batch_5"],
             "test": ["test_batch"], "label_key": b"labels"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if data_file is None:
            if download:
                raise RuntimeError(_NO_EGRESS)
            raise ValueError(f"Cifar needs data_file ({_NO_EGRESS})")
        self.mode = mode
        self.transform = transform
        names = self._META["batches"] if mode == "train" else self._META["test"]
        datas, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                base = os.path.basename(member.name)
                if base in names:
                    d = pickle.load(tf.extractfile(member), encoding="bytes")
                    datas.append(d[b"data"])
                    labels.extend(d[self._META["label_key"]])
        if not datas:
            raise ValueError(f"no {names} members found in {data_file}")
        self.data = np.concatenate(datas, 0)
        self.labels = np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.data[idx].reshape(3, 32, 32).transpose(1, 2, 0).astype("float32")
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self.labels[idx]], dtype=np.int64)

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    _META = {"batches": ["train"], "test": ["test"], "label_key": b"fine_labels"}


IMG_EXTENSIONS = (".npy", ".png", ".jpg", ".jpeg", ".bmp", ".ppm")


def _load_image(path):
    """numpy-backed loader: .npy natively; PNG/JPEG via PIL if available."""
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image

        return np.asarray(Image.open(path).convert("RGB"))
    except ImportError as e:
        raise ImportError(
            f"decoding {path!r} needs PIL; use .npy images in this build") from e


class DatasetFolder(Dataset):
    """class-per-subdirectory layout (reference folder.py:DatasetFolder)."""

    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None, is_valid_file=None):
        self.root = root
        self.loader = loader or _load_image
        self.transform = transform
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise ValueError(f"no class directories under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    p = os.path.join(dirpath, fname)
                    ok = (is_valid_file(p) if is_valid_file
                          else p.lower().endswith(tuple(extensions)))
                    if ok:
                        self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise ValueError(f"no valid samples under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """flat folder of images, no labels (reference folder.py:ImageFolder)."""

    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None, is_valid_file=None):
        self.root = root
        self.loader = loader or _load_image
        self.transform = transform
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                p = os.path.join(dirpath, fname)
                ok = (is_valid_file(p) if is_valid_file
                      else p.lower().endswith(tuple(extensions)))
                if ok:
                    self.samples.append(p)
        if not self.samples:
            raise ValueError(f"no valid images under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class _TarIndex:
    """Per-process cached tarfile handle: the member index is built once at
    first use in each process (fork-safe for DataLoader workers — handles are
    not shared across pids), so __getitem__ is an O(1) seek, not a fresh
    archive scan (review finding: reopening per sample is quadratic I/O)."""

    def __init__(self, path):
        self.path = path
        self._handles = {}

    def extract(self, name):
        import os as _os

        pid = _os.getpid()
        tf = self._handles.get(pid)
        if tf is None:
            tf = self._handles[pid] = tarfile.open(self.path, "r:*")
        return tf.extractfile(name).read()

    def __getstate__(self):
        return {"path": self.path}

    def __setstate__(self, state):
        self.path = state["path"]
        self._handles = {}


class Flowers(Dataset):
    """Oxford 102 Flowers (reference flowers.py). Local files only:
    ``data_file`` = 102flowers.tgz (jpg/image_XXXXX.jpg members),
    ``label_file`` = imagelabels.mat, ``setid_file`` = setid.mat.
    scipy-free .mat reading via a tiny MAT5 parser for the two 1-D arrays."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False, backend=None):
        if data_file is None or label_file is None or setid_file is None:
            if download:
                raise RuntimeError(_NO_EGRESS)
            raise ValueError(
                f"Flowers needs data_file, label_file and setid_file "
                f"({_NO_EGRESS})")
        self.transform = transform
        labels = self._mat_int_array(label_file)
        ids = self._mat_split_ids(setid_file, mode)
        self._names = {}
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                if m.name.endswith(".jpg"):
                    # image_00001.jpg -> 1
                    num = int(m.name.split("_")[-1].split(".")[0])
                    self._names[num] = m.name
        self._tar = _TarIndex(data_file)
        self.indexes = [i for i in ids if i in self._names]
        self.labels = {i: int(labels[i - 1]) - 1 for i in self.indexes}

    @staticmethod
    def _mat_int_array(path):
        """Read the single numeric matrix out of a MAT5 file (imagelabels.mat
        holds one 1xN uint8/uint16/double array)."""
        import io as _io

        with open(path, "rb") as f:
            f.seek(128)  # header
            data = f.read()
        arrs = Flowers._parse_mat_elements(data)
        if not arrs:
            raise ValueError(f"no numeric array found in {path}")
        return arrs[0].ravel()

    @staticmethod
    def _mat_split_ids(path, mode):
        with open(path, "rb") as f:
            f.seek(128)
            data = f.read()
        arrs = Flowers._parse_mat_elements(data)
        # setid.mat: trnid, valid, tstid (reference: train=trnid, valid=valid,
        # test=tstid) in file order
        key = {"train": 0, "valid": 1, "test": 2}[mode]
        if len(arrs) <= key:
            raise ValueError(f"setid.mat lacks split {mode}")
        return [int(v) for v in arrs[key].ravel()]

    @staticmethod
    def _parse_mat_elements(data):
        """Minimal MAT5 reader: walks top-level miMATRIX elements, returns
        their numeric payloads (handles miUINT8/16/32, miINT variants,
        miDOUBLE; zlib-compressed elements supported)."""
        import struct as _st
        import zlib

        type_fmt = {1: ("b", 1), 2: ("B", 1), 3: ("h", 2), 4: ("H", 2),
                    5: ("i", 4), 6: ("I", 4), 9: ("d", 8), 7: ("f", 4)}
        out = []

        def walk(buf):
            off = 0
            while off + 8 <= len(buf):
                dtype, nbytes = _st.unpack_from("<II", buf, off)
                small = dtype >> 16
                if small:  # small data element
                    payload = buf[off + 4:off + 8]
                    dtype &= 0xFFFF
                    nbytes = small
                    step = 8
                else:
                    payload = buf[off + 8:off + 8 + nbytes]
                    step = 8 + ((nbytes + 7) // 8) * 8
                if dtype == 15:  # miCOMPRESSED
                    walk(zlib.decompress(payload))
                elif dtype == 14:  # miMATRIX: flags(16) dims name data
                    walk_matrix(payload)
                elif dtype in type_fmt:
                    fmt, size = type_fmt[dtype]
                    n = nbytes // size
                    out.append(np.asarray(
                        _st.unpack_from(f"<{n}{fmt}", payload, 0)))
                off += step
            return out

        def walk_matrix(buf):
            off = 0
            seen_numeric = []
            while off + 8 <= len(buf):
                dtype, nbytes = _st.unpack_from("<II", buf, off)
                small = dtype >> 16
                if small:
                    payload = buf[off + 4:off + 8]
                    dtype &= 0xFFFF
                    nbytes = small
                    step = 8
                else:
                    payload = buf[off + 8:off + 8 + nbytes]
                    step = 8 + ((nbytes + 7) // 8) * 8
                if dtype in type_fmt and nbytes:
                    fmt, size = type_fmt[dtype]
                    n = nbytes // size
                    seen_numeric.append(np.asarray(
                        _st.unpack_from(f"<{n}{fmt}", payload, 0)))
                off += step
            # miMATRIX payload order: flags, dims, name, real data — the
            # LAST numeric block is the data
            if len(seen_numeric) >= 4:
                out.append(seen_numeric[-1])
            elif seen_numeric:
                out.append(seen_numeric[-1])

        walk(data)
        return out

    def __getitem__(self, idx):
        import io as _io

        from PIL import Image

        num = self.indexes[idx]
        img = Image.open(_io.BytesIO(
            self._tar.extract(self._names[num]))).convert("RGB")
        arr = np.asarray(img)
        if self.transform is not None:
            arr = self.transform(arr)
        return arr, np.int64(self.labels[num])

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (reference voc2012.py): the VOCtrainval
    tar with JPEGImages/, SegmentationClass/ and ImageSets/Segmentation/
    {train,val,trainval}.txt. Yields (image, label_mask) numpy pairs."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if data_file is None:
            if download:
                raise RuntimeError(_NO_EGRESS)
            raise ValueError(f"VOC2012 needs data_file ({_NO_EGRESS})")
        self.transform = transform
        self._tar = _TarIndex(data_file)
        mode = "train" if mode == "train" else ("val" if mode in ("val", "valid", "test") else mode)
        with tarfile.open(data_file, "r:*") as tf:
            names = tf.getnames()
            split = [n for n in names
                     if n.endswith(f"ImageSets/Segmentation/{mode}.txt")]
            if not split:
                raise ValueError(f"archive lacks the {mode} split list")
            ids = tf.extractfile(split[0]).read().decode().split()
            self._jpg = {}
            self._png = {}
            for n in names:
                base = os.path.basename(n)
                if n.endswith(".jpg") and "JPEGImages" in n:
                    self._jpg[base[:-4]] = n
                elif n.endswith(".png") and "SegmentationClass" in n:
                    self._png[base[:-4]] = n
        self.ids = [i for i in ids if i in self._jpg and i in self._png]

    def __getitem__(self, idx):
        import io as _io

        from PIL import Image

        key = self.ids[idx]
        img = Image.open(_io.BytesIO(
            self._tar.extract(self._jpg[key]))).convert("RGB")
        lab = Image.open(_io.BytesIO(self._tar.extract(self._png[key])))
        arr, mask = np.asarray(img), np.asarray(lab)
        if self.transform is not None:
            arr = self.transform(arr)
        return arr, mask

    def __len__(self):
        return len(self.ids)
