"""paddle.vision.datasets — dataset parsers for the standard vision corpora.

Reference: python/paddle/vision/datasets/ (MNIST idx-format parser mnist.py:190,
CIFAR tar-of-pickles cifar.py, folder.py DatasetFolder/ImageFolder). This
environment has zero network egress, so ``download=True`` raises with
instructions; all parsers consume local files in the exact upstream formats
(tests synthesize them). Decoding is numpy-only — no PIL dependency; the
'cv2'/'pil' backend knobs map to numpy HWC arrays.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder"]

_NO_EGRESS = ("this build has no network egress: pass image_path/label_path "
              "(or data_file) pointing at already-downloaded files instead of "
              "download=True")


def _maybe_open(path):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


class MNIST(Dataset):
    """idx-format parser (reference mnist.py:190 _parse_dataset).

    ``image_path``/``label_path``: local idx3-ubyte / idx1-ubyte files
    (optionally .gz). mode: 'train' | 'test' (used only for default names).
    """

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if image_path is None or label_path is None:
            if download:
                raise RuntimeError(_NO_EGRESS)
            raise ValueError("MNIST needs image_path and label_path "
                             f"({_NO_EGRESS})")
        self.mode = mode
        self.transform = transform
        self.images = self._parse_images(image_path)
        self.labels = self._parse_labels(label_path)
        assert len(self.images) == len(self.labels), "image/label count mismatch"

    @staticmethod
    def _parse_images(path):
        with _maybe_open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad idx3 magic {magic:#x} in {path}")
            buf = f.read(n * rows * cols)
        return np.frombuffer(buf, dtype=np.uint8).reshape(n, rows, cols)

    @staticmethod
    def _parse_labels(path):
        with _maybe_open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad idx1 magic {magic:#x} in {path}")
            buf = f.read(n)
        return np.frombuffer(buf, dtype=np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32")[..., None]  # HWC
        label = np.array([self.labels[idx]], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """CIFAR tar-of-pickled-batches parser (reference cifar.py).

    ``data_file``: local cifar-10-python.tar.gz (or an uncompressed .tar).
    """

    _META = {"batches": ["data_batch_1", "data_batch_2", "data_batch_3",
                         "data_batch_4", "data_batch_5"],
             "test": ["test_batch"], "label_key": b"labels"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if data_file is None:
            if download:
                raise RuntimeError(_NO_EGRESS)
            raise ValueError(f"Cifar needs data_file ({_NO_EGRESS})")
        self.mode = mode
        self.transform = transform
        names = self._META["batches"] if mode == "train" else self._META["test"]
        datas, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                base = os.path.basename(member.name)
                if base in names:
                    d = pickle.load(tf.extractfile(member), encoding="bytes")
                    datas.append(d[b"data"])
                    labels.extend(d[self._META["label_key"]])
        if not datas:
            raise ValueError(f"no {names} members found in {data_file}")
        self.data = np.concatenate(datas, 0)
        self.labels = np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.data[idx].reshape(3, 32, 32).transpose(1, 2, 0).astype("float32")
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self.labels[idx]], dtype=np.int64)

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    _META = {"batches": ["train"], "test": ["test"], "label_key": b"fine_labels"}


IMG_EXTENSIONS = (".npy", ".png", ".jpg", ".jpeg", ".bmp", ".ppm")


def _load_image(path):
    """numpy-backed loader: .npy natively; PNG/JPEG via PIL if available."""
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image

        return np.asarray(Image.open(path).convert("RGB"))
    except ImportError as e:
        raise ImportError(
            f"decoding {path!r} needs PIL; use .npy images in this build") from e


class DatasetFolder(Dataset):
    """class-per-subdirectory layout (reference folder.py:DatasetFolder)."""

    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None, is_valid_file=None):
        self.root = root
        self.loader = loader or _load_image
        self.transform = transform
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise ValueError(f"no class directories under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    p = os.path.join(dirpath, fname)
                    ok = (is_valid_file(p) if is_valid_file
                          else p.lower().endswith(tuple(extensions)))
                    if ok:
                        self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise ValueError(f"no valid samples under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """flat folder of images, no labels (reference folder.py:ImageFolder)."""

    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None, is_valid_file=None):
        self.root = root
        self.loader = loader or _load_image
        self.transform = transform
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                p = os.path.join(dirpath, fname)
                ok = (is_valid_file(p) if is_valid_file
                      else p.lower().endswith(tuple(extensions)))
                if ok:
                    self.samples.append(p)
        if not self.samples:
            raise ValueError(f"no valid images under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)
