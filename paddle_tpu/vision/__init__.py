"""paddle.vision. Reference: python/paddle/vision/."""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401


_image_backend = "pil"


def set_image_backend(backend):
    """Reference: vision/image.py — 'pil' | 'cv2' | 'tensor' dataset decode
    backend. PIL ships in this build; cv2 accepted if importable."""
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"backend must be 'pil'/'cv2'/'tensor', got {backend!r}")
    if backend == "cv2":
        try:
            import cv2  # noqa: F401
        except ImportError as e:
            raise ValueError("cv2 backend requested but not installed") from e
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Reference: vision/image.py image_load."""
    b = backend or _image_backend
    if b == "pil":
        from PIL import Image

        return Image.open(path)
    if b == "cv2":
        import cv2

        return cv2.imread(path)
    # tensor backend: decoded chw uint8 tensor
    import numpy as np

    from PIL import Image

    from ..tensor import Tensor
    import jax.numpy as jnp

    arr = np.asarray(Image.open(path).convert("RGB"))
    return Tensor(jnp.asarray(arr.transpose(2, 0, 1)))
