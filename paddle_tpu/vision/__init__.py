"""paddle.vision. Reference: python/paddle/vision/."""
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401
