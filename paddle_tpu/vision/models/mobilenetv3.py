"""MobileNetV3 Small/Large. Reference: python/paddle/vision/models/mobilenetv3.py
(API-identical: MobileNetV3Small/Large(scale, num_classes, with_pool),
mobilenet_v3_small/large). SE blocks + hardswish — ops the ResNet path never
touches (VERDICT round-3 gap list)."""
from __future__ import annotations

from ...nn import (
    AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Dropout, Hardsigmoid, Hardswish,
    Layer, Linear, ReLU, Sequential,
)
from ...ops.manipulation import flatten

__all__ = ["MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


from ._utils import _make_divisible  # noqa: E402


class SqueezeExcitation(Layer):
    """Reference: mobilenetv3.py:55."""

    def __init__(self, input_channels, squeeze_channels):
        super().__init__()
        self.avgpool = AdaptiveAvgPool2D(1)
        self.fc1 = Conv2D(input_channels, squeeze_channels, 1)
        self.relu = ReLU()
        self.fc2 = Conv2D(squeeze_channels, input_channels, 1)
        self.hardsigmoid = Hardsigmoid()

    def forward(self, x):
        scale = self.avgpool(x)
        scale = self.relu(self.fc1(scale))
        scale = self.hardsigmoid(self.fc2(scale))
        return x * scale


class _ConvBNAct(Sequential):
    def __init__(self, in_c, out_c, kernel, stride=1, groups=1, act=None):
        layers = [
            Conv2D(in_c, out_c, kernel, stride=stride,
                   padding=(kernel - 1) // 2, groups=groups, bias_attr=False),
            BatchNorm2D(out_c),
        ]
        if act == "relu":
            layers.append(ReLU())
        elif act == "hardswish":
            layers.append(Hardswish())
        super().__init__(*layers)


class InvertedResidual(Layer):
    """Reference: mobilenetv3.py:131 (expand -> dw -> optional SE -> project)."""

    def __init__(self, in_c, expanded_c, out_c, kernel, stride, use_se, act):
        super().__init__()
        self.use_res_connect = stride == 1 and in_c == out_c
        layers = []
        if expanded_c != in_c:
            layers.append(_ConvBNAct(in_c, expanded_c, 1, act=act))
        layers.append(_ConvBNAct(expanded_c, expanded_c, kernel, stride=stride,
                                 groups=expanded_c, act=act))
        if use_se:
            layers.append(SqueezeExcitation(
                expanded_c, _make_divisible(expanded_c // 4)))
        layers.append(_ConvBNAct(expanded_c, out_c, 1, act=None))
        self.block = Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        if self.use_res_connect:
            out = out + x
        return out


class MobileNetV3(Layer):
    """Reference: mobilenetv3.py:200."""

    def __init__(self, cfg, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return _make_divisible(ch * scale)

        first_c = c(cfg[0][0])
        layers = [_ConvBNAct(3, first_c, 3, stride=2, act="hardswish")]
        for in_c, exp_c, out_c, kernel, stride, use_se, act in cfg:
            layers.append(InvertedResidual(
                c(in_c), c(exp_c), c(out_c), kernel, stride, use_se, act))
        last_conv_in = c(cfg[-1][2])
        last_conv_out = c(cfg[-1][1])
        layers.append(_ConvBNAct(last_conv_in, last_conv_out, 1,
                                 act="hardswish"))
        self.features = Sequential(*layers)
        self.last_conv_out = last_conv_out
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(last_conv_out, last_channel),
                Hardswish(),
                Dropout(0.2),
                Linear(last_channel, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.classifier(x)
        return x


# (in, expanded, out, kernel, stride, use_se, activation)
_SMALL_CFG = [
    (16, 16, 16, 3, 2, True, "relu"),
    (16, 72, 24, 3, 2, False, "relu"),
    (24, 88, 24, 3, 1, False, "relu"),
    (24, 96, 40, 5, 2, True, "hardswish"),
    (40, 240, 40, 5, 1, True, "hardswish"),
    (40, 240, 40, 5, 1, True, "hardswish"),
    (40, 120, 48, 5, 1, True, "hardswish"),
    (48, 144, 48, 5, 1, True, "hardswish"),
    (48, 288, 96, 5, 2, True, "hardswish"),
    (96, 576, 96, 5, 1, True, "hardswish"),
    (96, 576, 96, 5, 1, True, "hardswish"),
]

_LARGE_CFG = [
    (16, 16, 16, 3, 1, False, "relu"),
    (16, 64, 24, 3, 2, False, "relu"),
    (24, 72, 24, 3, 1, False, "relu"),
    (24, 72, 40, 5, 2, True, "relu"),
    (40, 120, 40, 5, 1, True, "relu"),
    (40, 120, 40, 5, 1, True, "relu"),
    (40, 240, 80, 3, 2, False, "hardswish"),
    (80, 200, 80, 3, 1, False, "hardswish"),
    (80, 184, 80, 3, 1, False, "hardswish"),
    (80, 184, 80, 3, 1, False, "hardswish"),
    (80, 480, 112, 3, 1, True, "hardswish"),
    (112, 672, 112, 3, 1, True, "hardswish"),
    (112, 672, 160, 5, 2, True, "hardswish"),
    (160, 960, 160, 5, 1, True, "hardswish"),
    (160, 960, 160, 5, 1, True, "hardswish"),
]


class MobileNetV3Small(MobileNetV3):
    """Reference: mobilenetv3.py:301."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL_CFG, last_channel=1024, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


class MobileNetV3Large(MobileNetV3):
    """Reference: mobilenetv3.py:359."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE_CFG, last_channel=1280, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    model = MobileNetV3Small(scale=scale, **kwargs)
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a converted state_dict")
    return model


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    model = MobileNetV3Large(scale=scale, **kwargs)
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a converted state_dict")
    return model
