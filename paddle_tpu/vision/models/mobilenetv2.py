"""MobileNetV2. Reference: python/paddle/vision/models/mobilenetv2.py
(API-identical: MobileNetV2(scale, num_classes, with_pool), mobilenet_v2)."""
from __future__ import annotations

from ...nn import (
    AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Dropout, Layer, Linear, ReLU6,
    Sequential,
)
from ...ops.manipulation import flatten

__all__ = ["MobileNetV2", "mobilenet_v2"]


from ._utils import _make_divisible  # noqa: E402


class _ConvBNReLU(Sequential):
    def __init__(self, in_planes, out_planes, kernel_size=3, stride=1,
                 groups=1):
        padding = (kernel_size - 1) // 2
        super().__init__(
            Conv2D(in_planes, out_planes, kernel_size, stride=stride,
                   padding=padding, groups=groups, bias_attr=False),
            BatchNorm2D(out_planes),
            ReLU6(),
        )


class InvertedResidual(Layer):
    """expand 1x1 -> depthwise 3x3 -> project 1x1 (+skip). Ref: mobilenetv2.py:50."""

    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden_dim = int(round(inp * expand_ratio))
        self.use_res_connect = stride == 1 and inp == oup

        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(inp, hidden_dim, kernel_size=1))
        layers.extend([
            _ConvBNReLU(hidden_dim, hidden_dim, stride=stride,
                        groups=hidden_dim),
            Conv2D(hidden_dim, oup, 1, bias_attr=False),
            BatchNorm2D(oup),
        ])
        self.conv = Sequential(*layers)

    def forward(self, x):
        if self.use_res_connect:
            return x + self.conv(x)
        return self.conv(x)


class MobileNetV2(Layer):
    """Reference: mobilenetv2.py:100."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        input_channel = 32
        last_channel = 1280
        # t (expand), c (channels), n (repeats), s (stride)
        cfg = [
            [1, 16, 1, 1],
            [6, 24, 2, 2],
            [6, 32, 3, 2],
            [6, 64, 4, 2],
            [6, 96, 3, 1],
            [6, 160, 3, 2],
            [6, 320, 1, 1],
        ]
        input_channel = _make_divisible(input_channel * scale)
        self.last_channel = _make_divisible(last_channel * max(1.0, scale))
        features = [_ConvBNReLU(3, input_channel, stride=2)]
        for t, c, n, s in cfg:
            output_channel = _make_divisible(c * scale)
            for i in range(n):
                features.append(InvertedResidual(
                    input_channel, output_channel, s if i == 0 else 1, t))
                input_channel = output_channel
        features.append(_ConvBNReLU(input_channel, self.last_channel,
                                    kernel_size=1))
        self.features = Sequential(*features)
        if with_pool:
            self.pool2d_avg = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = Sequential(
                Dropout(0.2), Linear(self.last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    model = MobileNetV2(scale=scale, **kwargs)
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a converted state_dict")
    return model
