"""GoogLeNet (Inception v1). Reference: python/paddle/vision/models/googlenet.py
(API-identical: GoogLeNet(num_classes, with_pool); forward returns
(out, aux1, aux2) like the reference's googlenet.py:256)."""
from __future__ import annotations

from ...nn import (
    AdaptiveAvgPool2D, AvgPool2D, Conv2D, Dropout, Layer, Linear, MaxPool2D,
    ReLU, Sequential,
)
from ...ops.manipulation import concat, flatten

__all__ = ["GoogLeNet", "googlenet"]


class _ConvReLU(Sequential):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0):
        super().__init__(
            Conv2D(in_c, out_c, kernel, stride=stride, padding=padding),
            ReLU(),
        )


class Inception(Layer):
    """Four parallel branches concatenated on channels. Ref: googlenet.py:90."""

    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.branch1 = _ConvReLU(in_c, c1, 1)
        self.branch2 = Sequential(_ConvReLU(in_c, c3r, 1),
                                  _ConvReLU(c3r, c3, 3, padding=1))
        self.branch3 = Sequential(_ConvReLU(in_c, c5r, 1),
                                  _ConvReLU(c5r, c5, 5, padding=2))
        self.branch4 = Sequential(MaxPool2D(3, stride=1, padding=1),
                                  _ConvReLU(in_c, proj, 1))

    def forward(self, x):
        return concat([self.branch1(x), self.branch2(x), self.branch3(x),
                       self.branch4(x)], axis=1)


class _AuxHead(Layer):
    def __init__(self, in_c, num_classes):
        super().__init__()
        self.pool = AvgPool2D(5, stride=3)
        self.conv = _ConvReLU(in_c, 128, 1)
        self.fc1 = Linear(128 * 4 * 4, 1024)
        self.relu = ReLU()
        self.drop = Dropout(0.7)
        self.fc2 = Linear(1024, num_classes)

    def forward(self, x):
        x = self.conv(self.pool(x))
        x = flatten(x, 1)
        x = self.drop(self.relu(self.fc1(x)))
        return self.fc2(x)


class GoogLeNet(Layer):
    """Reference: googlenet.py:130."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.stem = Sequential(
            _ConvReLU(3, 64, 7, stride=2, padding=3),
            MaxPool2D(3, stride=2, ceil_mode=True),
            _ConvReLU(64, 64, 1),
            _ConvReLU(64, 192, 3, padding=1),
            MaxPool2D(3, stride=2, ceil_mode=True),
        )
        self.inc3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.inc3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = MaxPool2D(3, stride=2, ceil_mode=True)
        self.inc4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.inc4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.inc4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.inc4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.inc4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = MaxPool2D(3, stride=2, ceil_mode=True)
        self.inc5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.inc5b = Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.drop = Dropout(0.4)
            self.fc = Linear(1024, num_classes)
            self.aux1 = _AuxHead(512, num_classes)
            self.aux2 = _AuxHead(528, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.inc3b(self.inc3a(x)))
        x = self.inc4a(x)
        aux1 = self.aux1(x) if self.num_classes > 0 else None
        x = self.inc4d(self.inc4c(self.inc4b(x)))
        aux2 = self.aux2(x) if self.num_classes > 0 else None
        x = self.pool4(self.inc4e(x))
        x = self.inc5b(self.inc5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.fc(self.drop(x))
            return x, aux1, aux2
        return x


def googlenet(pretrained=False, **kwargs):
    model = GoogLeNet(**kwargs)
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a converted state_dict")
    return model
