"""SqueezeNet. Reference: python/paddle/vision/models/squeezenet.py
(API-identical: SqueezeNet(version, num_classes, with_pool), squeezenet1_0/1_1)."""
from __future__ import annotations

from ...nn import (
    AdaptiveAvgPool2D, Conv2D, Dropout, Layer, MaxPool2D, ReLU, Sequential,
)
from ...ops.manipulation import concat, flatten

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class _Fire(Layer):
    """squeeze 1x1 -> expand 1x1 + expand 3x3, concatenated on channels."""

    def __init__(self, in_channels, squeeze, expand1x1, expand3x3):
        super().__init__()
        self.squeeze = Conv2D(in_channels, squeeze, 1)
        self.relu = ReLU()
        self.expand1x1 = Conv2D(squeeze, expand1x1, 1)
        self.expand3x3 = Conv2D(squeeze, expand3x3, 3, padding=1)

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        a = self.relu(self.expand1x1(x))
        b = self.relu(self.expand3x3(x))
        return concat([a, b], axis=1)


class SqueezeNet(Layer):
    """Reference: squeezenet.py (class SqueezeNet)."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        if version not in ("1.0", "1.1"):
            raise ValueError("version must be '1.0' or '1.1'")
        self.version = version
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = Sequential(
                Conv2D(3, 96, 7, stride=2), ReLU(),
                MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(96, 16, 64, 64),
                _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128),
                MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(256, 32, 128, 128),
                _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256),
                MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(512, 64, 256, 256),
            )
        else:
            self.features = Sequential(
                Conv2D(3, 64, 3, stride=2), ReLU(),
                MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(64, 16, 64, 64),
                _Fire(128, 16, 64, 64),
                MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(128, 32, 128, 128),
                _Fire(256, 32, 128, 128),
                MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256),
                _Fire(512, 64, 256, 256),
            )
        if num_classes > 0:
            self.classifier = Sequential(
                Dropout(0.5),
                Conv2D(512, num_classes, 1),
                ReLU(),
            )
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.avgpool(x)
        return flatten(x, 1) if self.num_classes > 0 else x


def _squeezenet(version, pretrained, **kwargs):
    model = SqueezeNet(version, **kwargs)
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a converted state_dict")
    return model


def squeezenet1_0(pretrained=False, **kwargs):
    return _squeezenet("1.0", pretrained, **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return _squeezenet("1.1", pretrained, **kwargs)
