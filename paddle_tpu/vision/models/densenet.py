"""DenseNet family. Reference: python/paddle/vision/models/densenet.py
(API-identical: DenseNet(layers, bn_size, dropout, num_classes, with_pool),
densenet121/161/169/201/264). Pre-activation BN-ReLU-Conv dense layers with
channel concatenation."""
from __future__ import annotations

from ...nn import (
    AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D, Conv2D, Dropout, Layer, Linear,
    MaxPool2D, ReLU, Sequential,
)
from ...ops.manipulation import concat, flatten

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFG = {
    121: ((6, 12, 24, 16), 32, 64),
    161: ((6, 12, 36, 24), 48, 96),
    169: ((6, 12, 32, 32), 32, 64),
    201: ((6, 12, 48, 32), 32, 64),
    264: ((6, 12, 64, 48), 32, 64),
}


class _DenseLayer(Layer):
    """BN-ReLU-Conv1x1 (bottleneck) -> BN-ReLU-Conv3x3 (growth). Ref:
    densenet.py:116."""

    def __init__(self, num_channels, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = BatchNorm2D(num_channels)
        self.relu = ReLU()
        self.conv1 = Conv2D(num_channels, bn_size * growth_rate, 1,
                            bias_attr=False)
        self.bn2 = BatchNorm2D(bn_size * growth_rate)
        self.conv2 = Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1,
                            bias_attr=False)
        self.dropout = Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class _Transition(Layer):
    """BN-ReLU-Conv1x1 (halve channels) + 2x2 avgpool. Ref: densenet.py:191."""

    def __init__(self, num_channels, num_output_features):
        super().__init__()
        self.bn = BatchNorm2D(num_channels)
        self.relu = ReLU()
        self.conv = Conv2D(num_channels, num_output_features, 1,
                           bias_attr=False)
        self.pool = AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(Layer):
    """Reference: densenet.py:242."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        if layers not in _CFG:
            raise ValueError(f"layers must be one of {sorted(_CFG)}")
        block_config, growth_rate, num_init_features = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.stem = Sequential(
            Conv2D(3, num_init_features, 7, stride=2, padding=3,
                   bias_attr=False),
            BatchNorm2D(num_init_features),
            ReLU(),
            MaxPool2D(3, stride=2, padding=1),
        )
        blocks = []
        num_channels = num_init_features
        for i, num_layers in enumerate(block_config):
            for j in range(num_layers):
                blocks.append(_DenseLayer(
                    num_channels + j * growth_rate, growth_rate, bn_size,
                    dropout))
            num_channels += num_layers * growth_rate
            if i != len(block_config) - 1:
                blocks.append(_Transition(num_channels, num_channels // 2))
                num_channels //= 2
        self.blocks = Sequential(*blocks)
        self.bn_final = BatchNorm2D(num_channels)
        self.relu_final = ReLU()
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = Linear(num_channels, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.blocks(x)
        x = self.relu_final(self.bn_final(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.classifier(x)
        return x


def _densenet(layers, pretrained, **kwargs):
    model = DenseNet(layers=layers, **kwargs)
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a converted state_dict")
    return model


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
