"""ShuffleNetV2. Reference: python/paddle/vision/models/shufflenetv2.py
(API-identical: ShuffleNetV2(scale, act, num_classes, with_pool) + the seven
shufflenet_v2_* constructors). Exercises channel_shuffle (reshape/transpose
data movement) and channel-split residuals."""
from __future__ import annotations

from ...nn import (
    AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Layer, Linear, MaxPool2D, ReLU,
    Sequential, Swish,
)
from ...ops.manipulation import concat, flatten, reshape, split, transpose

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]

_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512],
    0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024],
    2.0: [24, 244, 488, 976, 2048],
}
_STAGE_REPEATS = [4, 8, 4]


def channel_shuffle(x, groups):
    """Interleave channel groups (NCHW). Reference: shufflenetv2.py:101."""
    n, c, h, w = x.shape
    x = reshape(x, [n, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [n, c, h, w])


def _act(name):
    return Swish() if name == "swish" else ReLU()


class _ConvBNAct(Sequential):
    def __init__(self, in_c, out_c, kernel, stride=1, groups=1, act="relu"):
        layers = [
            Conv2D(in_c, out_c, kernel, stride=stride,
                   padding=(kernel - 1) // 2, groups=groups, bias_attr=False),
            BatchNorm2D(out_c),
        ]
        if act is not None:
            layers.append(_act(act))
        super().__init__(*layers)


class InvertedResidual(Layer):
    """Stride-1 unit: split channels, transform one half, shuffle.
    Reference: shufflenetv2.py:118."""

    def __init__(self, channels, act):
        super().__init__()
        half = channels // 2
        self.branch = Sequential(
            _ConvBNAct(half, half, 1, act=act),
            _ConvBNAct(half, half, 3, groups=half, act=None),  # depthwise
            _ConvBNAct(half, half, 1, act=act),
        )

    def forward(self, x):
        x1, x2 = split(x, 2, axis=1)
        out = concat([x1, self.branch(x2)], axis=1)
        return channel_shuffle(out, 2)


class InvertedResidualDS(Layer):
    """Stride-2 (downsample) unit: both halves transformed.
    Reference: shufflenetv2.py:168."""

    def __init__(self, in_c, out_c, act):
        super().__init__()
        half = out_c // 2
        self.branch1 = Sequential(
            _ConvBNAct(in_c, in_c, 3, stride=2, groups=in_c, act=None),
            _ConvBNAct(in_c, half, 1, act=act),
        )
        self.branch2 = Sequential(
            _ConvBNAct(in_c, half, 1, act=act),
            _ConvBNAct(half, half, 3, stride=2, groups=half, act=None),
            _ConvBNAct(half, half, 1, act=act),
        )

    def forward(self, x):
        out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(Layer):
    """Reference: shufflenetv2.py:237."""

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        if scale not in _STAGE_OUT:
            raise ValueError(f"scale must be one of {sorted(_STAGE_OUT)}")
        self.num_classes = num_classes
        self.with_pool = with_pool
        outs = _STAGE_OUT[scale]

        self.conv1 = _ConvBNAct(3, outs[0], 3, stride=2, act=act)
        self.max_pool = MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = outs[0]
        for stage_idx, repeats in enumerate(_STAGE_REPEATS):
            out_c = outs[stage_idx + 1]
            stages.append(InvertedResidualDS(in_c, out_c, act))
            for _ in range(repeats - 1):
                stages.append(InvertedResidual(out_c, act))
            in_c = out_c
        self.stages = Sequential(*stages)
        self.conv_last = _ConvBNAct(in_c, outs[-1], 1, act=act)
        if with_pool:
            self.pool2d_avg = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = Linear(outs[-1], num_classes)

    def forward(self, x):
        x = self.max_pool(self.conv1(x))
        x = self.stages(x)
        x = self.conv_last(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def _shufflenet(scale, act, pretrained, **kwargs):
    model = ShuffleNetV2(scale=scale, act=act, **kwargs)
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a converted state_dict")
    return model


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, "relu", pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, "swish", pretrained, **kwargs)
