"""InceptionV3. Reference: python/paddle/vision/models/inceptionv3.py
(API-identical: InceptionV3(num_classes, with_pool), inception_v3). 299x299
input; factorized 7x1/1x7 and 3x1/1x3 convolutions (asymmetric-kernel ops the
ResNet path never exercises)."""
from __future__ import annotations

from ...nn import (
    AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D, Conv2D, Dropout, Layer, Linear,
    MaxPool2D, ReLU, Sequential,
)
from ...ops.manipulation import concat, flatten

__all__ = ["InceptionV3", "inception_v3"]


class _ConvBN(Sequential):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0):
        super().__init__(
            Conv2D(in_c, out_c, kernel, stride=stride, padding=padding,
                   bias_attr=False),
            BatchNorm2D(out_c),
            ReLU(),
        )


class InceptionStem(Layer):
    """Reference: inceptionv3.py:55."""

    def __init__(self):
        super().__init__()
        self.conv1 = _ConvBN(3, 32, 3, stride=2)
        self.conv2 = _ConvBN(32, 32, 3)
        self.conv3 = _ConvBN(32, 64, 3, padding=1)
        self.pool1 = MaxPool2D(3, stride=2)
        self.conv4 = _ConvBN(64, 80, 1)
        self.conv5 = _ConvBN(80, 192, 3)
        self.pool2 = MaxPool2D(3, stride=2)

    def forward(self, x):
        x = self.pool1(self.conv3(self.conv2(self.conv1(x))))
        return self.pool2(self.conv5(self.conv4(x)))


class InceptionA(Layer):
    """Reference: inceptionv3.py:109."""

    def __init__(self, in_c, pool_features):
        super().__init__()
        self.b1 = _ConvBN(in_c, 64, 1)
        self.b5 = Sequential(_ConvBN(in_c, 48, 1),
                             _ConvBN(48, 64, 5, padding=2))
        self.b3 = Sequential(_ConvBN(in_c, 64, 1),
                             _ConvBN(64, 96, 3, padding=1),
                             _ConvBN(96, 96, 3, padding=1))
        self.pool = Sequential(AvgPool2D(3, stride=1, padding=1),
                               _ConvBN(in_c, pool_features, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b5(x), self.b3(x), self.pool(x)], 1)


class InceptionB(Layer):
    """Grid reduction 35->17. Reference: inceptionv3.py:185."""

    def __init__(self, in_c):
        super().__init__()
        self.b3 = _ConvBN(in_c, 384, 3, stride=2)
        self.b3dbl = Sequential(_ConvBN(in_c, 64, 1),
                                _ConvBN(64, 96, 3, padding=1),
                                _ConvBN(96, 96, 3, stride=2))
        self.pool = MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b3dbl(x), self.pool(x)], 1)


class InceptionC(Layer):
    """Factorized 7x7. Reference: inceptionv3.py:236."""

    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _ConvBN(in_c, 192, 1)
        self.b7 = Sequential(
            _ConvBN(in_c, c7, 1),
            _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBN(c7, 192, (7, 1), padding=(3, 0)))
        self.b7dbl = Sequential(
            _ConvBN(in_c, c7, 1),
            _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBN(c7, 192, (1, 7), padding=(0, 3)))
        self.pool = Sequential(AvgPool2D(3, stride=1, padding=1),
                               _ConvBN(in_c, 192, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b7(x), self.b7dbl(x), self.pool(x)], 1)


class InceptionD(Layer):
    """Grid reduction 17->8. Reference: inceptionv3.py:342."""

    def __init__(self, in_c):
        super().__init__()
        self.b3 = Sequential(_ConvBN(in_c, 192, 1),
                             _ConvBN(192, 320, 3, stride=2))
        self.b7x3 = Sequential(
            _ConvBN(in_c, 192, 1),
            _ConvBN(192, 192, (1, 7), padding=(0, 3)),
            _ConvBN(192, 192, (7, 1), padding=(3, 0)),
            _ConvBN(192, 192, 3, stride=2))
        self.pool = MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b7x3(x), self.pool(x)], 1)


class InceptionE(Layer):
    """Expanded-filter-bank output blocks. Reference: inceptionv3.py:408."""

    def __init__(self, in_c):
        super().__init__()
        self.b1 = _ConvBN(in_c, 320, 1)
        self.b3_1 = _ConvBN(in_c, 384, 1)
        self.b3_2a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_2b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b3dbl_1 = Sequential(_ConvBN(in_c, 448, 1),
                                  _ConvBN(448, 384, 3, padding=1))
        self.b3dbl_2a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3dbl_2b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.pool = Sequential(AvgPool2D(3, stride=1, padding=1),
                               _ConvBN(in_c, 192, 1))

    def forward(self, x):
        b3 = self.b3_1(x)
        b3 = concat([self.b3_2a(b3), self.b3_2b(b3)], 1)
        b3dbl = self.b3dbl_1(x)
        b3dbl = concat([self.b3dbl_2a(b3dbl), self.b3dbl_2b(b3dbl)], 1)
        return concat([self.b1(x), b3, b3dbl, self.pool(x)], 1)


class InceptionV3(Layer):
    """Reference: inceptionv3.py:507."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = InceptionStem()
        self.blocks = Sequential(
            InceptionA(192, 32),
            InceptionA(256, 64),
            InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128),
            InceptionC(768, 160),
            InceptionC(768, 160),
            InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280),
            InceptionE(2048),
        )
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = Dropout(0.2)
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.dropout(x)
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def inception_v3(pretrained=False, **kwargs):
    model = InceptionV3(**kwargs)
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a converted state_dict")
    return model
