"""Shared model-zoo helpers. Reference: python/paddle/vision/models/_utils.py."""
from __future__ import annotations


def _make_divisible(v, divisor=8, min_value=None):
    """Round `v` to the nearest multiple of `divisor`, never dropping more
    than 10% (the MobileNet channel-rounding rule)."""
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v
