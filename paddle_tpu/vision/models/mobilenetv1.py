"""MobileNetV1. Reference: python/paddle/vision/models/mobilenetv1.py
(API-identical: MobileNetV1(scale, num_classes, with_pool), mobilenet_v1)."""
from __future__ import annotations

from ...nn import (
    AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Layer, Linear, ReLU, Sequential,
)
from ...ops.manipulation import flatten

__all__ = ["MobileNetV1", "mobilenet_v1"]


class _ConvBNRelu(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, groups=1):
        super().__init__()
        self.conv = Conv2D(in_channels, out_channels, kernel_size,
                           stride=stride, padding=padding, groups=groups,
                           bias_attr=False)
        self.bn = BatchNorm2D(out_channels)
        self.relu = ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class DepthwiseSeparable(Layer):
    """3x3 depthwise conv + 1x1 pointwise conv. Reference: mobilenetv1.py:50."""

    def __init__(self, in_channels, out_channels1, out_channels2, num_groups,
                 stride, scale):
        super().__init__()
        self._depthwise = _ConvBNRelu(
            in_channels, int(out_channels1 * scale), 3, stride=stride,
            padding=1, groups=int(num_groups * scale))
        self._pointwise = _ConvBNRelu(
            int(out_channels1 * scale), int(out_channels2 * scale), 1)

    def forward(self, x):
        return self._pointwise(self._depthwise(x))


class MobileNetV1(Layer):
    """Reference: mobilenetv1.py:85."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = _ConvBNRelu(3, int(32 * scale), 3, stride=2, padding=1)
        # (in, dw_out, pw_out, groups, stride) ladder of the 13 DS blocks
        cfg = [
            (32, 32, 64, 32, 1),
            (64, 64, 128, 64, 2),
            (128, 128, 128, 128, 1),
            (128, 128, 256, 128, 2),
            (256, 256, 256, 256, 1),
            (256, 256, 512, 256, 2),
            (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1),
            (512, 512, 1024, 512, 2),
            (1024, 1024, 1024, 1024, 1),
        ]
        blocks = [
            DepthwiseSeparable(int(i * scale), d, p, g, s, scale)
            for i, d, p, g, s in cfg
        ]
        self.blocks = Sequential(*blocks)
        if with_pool:
            self.pool2d_avg = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.conv1(x)
        x = self.blocks(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    model = MobileNetV1(scale=scale, **kwargs)
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a converted state_dict")
    return model
