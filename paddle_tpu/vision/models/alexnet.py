"""AlexNet. Reference: python/paddle/vision/models/alexnet.py (API-identical)."""
from __future__ import annotations

from ...nn import (
    Conv2D, Dropout, Flatten, Layer, Linear, MaxPool2D, ReLU, Sequential,
)

__all__ = ["AlexNet", "alexnet"]


class AlexNet(Layer):
    """Reference: alexnet.py:86 (conv stack with 3x3 maxpools + dropout head)."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = Sequential(
            Conv2D(3, 64, 11, stride=4, padding=2), ReLU(),
            MaxPool2D(3, stride=2),
            Conv2D(64, 192, 5, padding=2), ReLU(),
            MaxPool2D(3, stride=2),
            Conv2D(192, 384, 3, padding=1), ReLU(),
            Conv2D(384, 256, 3, padding=1), ReLU(),
            Conv2D(256, 256, 3, padding=1), ReLU(),
            MaxPool2D(3, stride=2),
        )
        if num_classes > 0:
            self.classifier = Sequential(
                Flatten(),
                Dropout(0.5),
                Linear(256 * 6 * 6, 4096), ReLU(),
                Dropout(0.5),
                Linear(4096, 4096), ReLU(),
                Linear(4096, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        return x


def alexnet(pretrained=False, **kwargs):
    model = AlexNet(**kwargs)
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a converted state_dict")
    return model
