"""Vision transforms (host-side numpy; the input pipeline runs on CPU).
Reference: python/paddle/vision/transforms/."""
from __future__ import annotations

import numbers

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop", "CenterCrop",
           "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
           "RandomResizedCrop", "BrightnessTransform", "to_tensor", "normalize",
           "resize", "hflip", "vflip", "center_crop", "crop"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def _as_np(img):
    return np.asarray(img)


def to_tensor(pic, data_format="CHW"):
    arr = _as_np(pic).astype(np.float32)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.max() > 1.5:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    from ...tensor import to_tensor as _tt

    return _tt(arr)


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    from ...tensor import Tensor

    is_tensor = isinstance(img, Tensor)
    arr = np.asarray(img._value if is_tensor else img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        arr = (arr - mean[:, None, None]) / std[:, None, None]
    else:
        arr = (arr - mean) / std
    if is_tensor:
        from ...tensor import to_tensor as _tt

        return _tt(arr)
    return arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std, self.data_format = mean, std, data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


def resize(img, size, interpolation="bilinear"):
    arr = _as_np(img)
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    import jax

    out_shape = (size[0], size[1]) + arr.shape[2:]
    method = {"bilinear": "bilinear", "nearest": "nearest", "bicubic": "bicubic"}[
        interpolation]
    return np.asarray(jax.image.resize(arr.astype(np.float32), out_shape, method=method))


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size, self.interpolation = size, interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


def crop(img, top, left, height, width):
    return _as_np(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _as_np(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    h, w = arr.shape[:2]
    th, tw = output_size
    top = (h - th) // 2
    left = (w - tw) // 2
    return crop(arr, top, left, th, tw)


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def __call__(self, img):
        arr = _as_np(img)
        if self.padding:
            p = self.padding
            arr = np.pad(arr, ((p, p), (p, p)) + ((0, 0),) * (arr.ndim - 2))
        h, w = arr.shape[:2]
        th, tw = self.size
        top = np.random.randint(0, max(h - th, 0) + 1)
        left = np.random.randint(0, max(w - tw, 0) + 1)
        return crop(arr, top, left, th, tw)


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.scale, self.ratio, self.interpolation = scale, ratio, interpolation

    def __call__(self, img):
        arr = _as_np(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = np.random.uniform(*self.scale) * area
            aspect = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if cw <= w and ch <= h:
                top = np.random.randint(0, h - ch + 1)
                left = np.random.randint(0, w - cw + 1)
                patch = crop(arr, top, left, ch, cw)
                return resize(patch, self.size, self.interpolation)
        return resize(center_crop(arr, min(h, w)), self.size, self.interpolation)


def hflip(img):
    return _as_np(img)[:, ::-1]


def vflip(img):
    return _as_np(img)[::-1]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return hflip(img)
        return _as_np(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return vflip(img)
        return _as_np(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = _as_np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill

    def __call__(self, img):
        arr = _as_np(img)
        p = self.padding
        if isinstance(p, int):
            p = (p, p, p, p)
        return np.pad(arr, ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (arr.ndim - 2),
                      constant_values=self.fill)


class BrightnessTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        arr = _as_np(img).astype(np.float32)
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(arr * alpha, 0, 255 if arr.max() > 1.5 else 1.0)

from ._extra import (  # noqa: E402,F401
    BaseTransform, ColorJitter, ContrastTransform, Grayscale, HueTransform,
    RandomAffine, RandomErasing, RandomPerspective, RandomRotation,
    SaturationTransform, adjust_brightness, adjust_contrast, adjust_hue,
    adjust_saturation, affine, erase, pad, perspective, rotate, to_grayscale,
)
