"""Round-5 transforms tail. Reference: python/paddle/vision/transforms/
(transforms.py + functional.py) — color ops, geometric warps (PIL backend,
matching the reference's default), random augmentations.

Convention follows the existing module: numpy HWC arrays in/out (PIL images
accepted), uint8 [0,255] or float [0,1]."""
from __future__ import annotations

import numbers

import numpy as np


def _as_np(img):
    return np.asarray(img)


def _is_float(arr):
    return arr.dtype.kind == "f" and arr.max() <= 1.5


def _to_pil(img):
    from PIL import Image

    arr = _as_np(img)
    if arr.dtype.kind == "f":
        arr = (np.clip(arr, 0, 1) * 255).astype(np.uint8)
    if arr.ndim == 3 and arr.shape[2] == 1:
        arr = arr[:, :, 0]
    return Image.fromarray(arr)


def _from_pil(pil, like):
    arr = np.asarray(pil)
    ref = _as_np(like)
    if arr.ndim == 2 and ref.ndim == 3:
        arr = arr[:, :, None]
    if ref.dtype.kind == "f" and ref.max() <= 1.5:
        arr = arr.astype(np.float32) / 255.0
    return arr


# ------------------------------------------------------------- color functional
def adjust_brightness(img, brightness_factor):
    """Reference functional.py adjust_brightness: img * factor."""
    arr = _as_np(img).astype(np.float32)
    hi = 1.0 if _is_float(_as_np(img)) else 255.0
    out = np.clip(arr * brightness_factor, 0, hi)
    return out if hi == 1.0 else out.astype(_as_np(img).dtype)


def adjust_contrast(img, contrast_factor):
    """Blend with the grayscale mean."""
    arr = _as_np(img).astype(np.float32)
    hi = 1.0 if _is_float(_as_np(img)) else 255.0
    gray = arr.mean(axis=tuple(range(arr.ndim)), keepdims=False) if arr.ndim == 2 \
        else (arr[..., :3] @ np.asarray([0.299, 0.587, 0.114], np.float32)).mean()
    out = np.clip((1 - contrast_factor) * gray + contrast_factor * arr, 0, hi)
    return out if hi == 1.0 else out.astype(_as_np(img).dtype)


def adjust_saturation(img, saturation_factor):
    """Blend with the per-pixel grayscale."""
    arr = _as_np(img).astype(np.float32)
    hi = 1.0 if _is_float(_as_np(img)) else 255.0
    gray = arr[..., :3] @ np.asarray([0.299, 0.587, 0.114], np.float32)
    out = np.clip((1 - saturation_factor) * gray[..., None]
                  + saturation_factor * arr, 0, hi)
    return out if hi == 1.0 else out.astype(_as_np(img).dtype)


def adjust_hue(img, hue_factor):
    """Rotate hue by hue_factor (in [-0.5, 0.5]) via HSV."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    src = _as_np(img)
    pil = _to_pil(img).convert("HSV")
    h, s, v = pil.split()
    h_arr = np.asarray(h, np.int16)
    h_arr = ((h_arr + int(hue_factor * 255)) % 256).astype(np.uint8)
    from PIL import Image

    out = Image.merge("HSV", (Image.fromarray(h_arr, "L"), s, v)).convert("RGB")
    return _from_pil(out, src)


def to_grayscale(img, num_output_channels=1):
    arr = _as_np(img).astype(np.float32)
    gray = arr[..., :3] @ np.asarray([0.299, 0.587, 0.114], np.float32)
    out = np.repeat(gray[..., None], num_output_channels, axis=-1)
    return out if _is_float(_as_np(img)) else out.astype(_as_np(img).dtype)


# --------------------------------------------------------- geometric functional
def _interp(mode):
    from PIL import Image

    return {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
            "bicubic": Image.BICUBIC}.get(mode, Image.NEAREST)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Reference functional.py rotate (PIL backend)."""
    pil = _to_pil(img)
    out = pil.rotate(angle, resample=_interp(interpolation), expand=expand,
                     center=center, fillcolor=fill)
    return _from_pil(out, img)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Reference functional.py affine: rotation+translation+scale+shear about
    the center (inverse-matrix form PIL consumes)."""
    import math

    arr = _as_np(img)
    h, w = arr.shape[0], arr.shape[1]
    cx, cy = center if center is not None else (w * 0.5, h * 0.5)
    rot = math.radians(angle)
    sx, sy = [math.radians(s) for s in (shear if isinstance(shear, (list, tuple))
                                        else (shear, 0.0))]
    # forward matrix M = T(center) R S Shear T(-center) T(translate)
    a = math.cos(rot - sy) / math.cos(sy)
    b = -math.cos(rot - sy) * math.tan(sx) / math.cos(sy) - math.sin(rot)
    c = math.sin(rot - sy) / math.cos(sy)
    d = -math.sin(rot - sy) * math.tan(sx) / math.cos(sy) + math.cos(rot)
    M = np.asarray([[a, b, 0.0], [c, d, 0.0], [0, 0, 1]], np.float64) * scale
    M[2, 2] = 1.0
    T1 = np.asarray([[1, 0, cx + translate[0]], [0, 1, cy + translate[1]],
                     [0, 0, 1]], np.float64)
    T2 = np.asarray([[1, 0, -cx], [0, 1, -cy], [0, 0, 1]], np.float64)
    fwd = T1 @ M @ T2
    inv = np.linalg.inv(fwd)
    pil = _to_pil(img)
    from PIL import Image

    out = pil.transform((w, h), Image.AFFINE,
                        (inv[0, 0], inv[0, 1], inv[0, 2],
                         inv[1, 0], inv[1, 1], inv[1, 2]),
                        resample=_interp(interpolation), fillcolor=fill)
    return _from_pil(out, img)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """Reference functional.py perspective: warp mapping endpoints back onto
    startpoints (PIL PERSPECTIVE coefficients solved least-squares)."""
    a = []
    b = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        a.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        b.extend([sx, sy])
    coeffs = np.linalg.lstsq(np.asarray(a, np.float64),
                             np.asarray(b, np.float64), rcond=None)[0]
    pil = _to_pil(img)
    from PIL import Image

    h, w = _as_np(img).shape[:2]
    out = pil.transform((w, h), Image.PERSPECTIVE, tuple(coeffs),
                        resample=_interp(interpolation), fillcolor=fill)
    return _from_pil(out, img)


def erase(img, i, j, h, w, v, inplace=False):
    """Reference functional.py erase: overwrite the (i:i+h, j:j+w) patch."""
    arr = _as_np(img)
    out = arr if inplace else arr.copy()
    out[i:i + h, j:j + w] = v
    return out


def pad(img, padding, fill=0, padding_mode="constant"):
    """Reference functional.py pad (left/top/right/bottom int or tuple)."""
    arr = _as_np(img)
    p = padding
    if isinstance(p, numbers.Number):
        p = (p, p, p, p)
    elif len(p) == 2:
        p = (p[0], p[1], p[0], p[1])
    widths = ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, widths, mode=mode, **kw)


# ----------------------------------------------------------- transform classes
class BaseTransform:
    """Reference transforms.py BaseTransform — keys-aware callable: applies
    _apply_image (and friends) to each element per `keys`."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def _get_params(self, inputs):
        return None

    def _apply_image(self, image):
        raise NotImplementedError

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            outs = []
            for key, data in zip(self.keys, inputs):
                fn = getattr(self, f"_apply_{key}", None)
                outs.append(fn(data) if fn else data)
            return tuple(outs)
        return self._apply_image(inputs)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_contrast(img,
                               np.random.uniform(max(0, 1 - self.value),
                                                 1 + self.value))


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("saturation value must be non-negative")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_saturation(img,
                                 np.random.uniform(max(0, 1 - self.value),
                                                   1 + self.value))


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """Reference transforms.py ColorJitter — random order of the four color
    perturbations."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    def _apply_image(self, img):
        from . import BrightnessTransform

        ops = []
        if self.brightness:
            ops.append(BrightnessTransform(self.brightness))
        if self.contrast:
            ops.append(ContrastTransform(self.contrast))
        if self.saturation:
            ops.append(SaturationTransform(self.saturation))
        if self.hue:
            ops.append(HueTransform(self.hue))
        for i in np.random.permutation(len(ops)):
            img = ops[int(i)](img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand,
                      self.center, self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        h, w = _as_np(img).shape[:2]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        scale = np.random.uniform(*self.scale) if self.scale else 1.0
        shear = 0.0
        if self.shear is not None:
            sh = self.shear
            if isinstance(sh, numbers.Number):
                sh = (-abs(sh), abs(sh))
            shear = np.random.uniform(sh[0], sh[1])
        return affine(img, angle, (tx, ty), scale, shear,
                      self.interpolation, self.fill, self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return _as_np(img)
        h, w = _as_np(img).shape[:2]
        d = self.distortion_scale
        half_h, half_w = int(h * d / 2), int(w * d / 2)

        def r(lo, hi):
            return int(np.random.randint(lo, max(lo + 1, hi)))

        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(r(0, half_w), r(0, half_h)),
               (w - 1 - r(0, half_w), r(0, half_h)),
               (w - 1 - r(0, half_w), h - 1 - r(0, half_h)),
               (r(0, half_w), h - 1 - r(0, half_h))]
        return perspective(img, start, end, self.interpolation, self.fill)


class RandomErasing(BaseTransform):
    """Reference transforms.py RandomErasing (Zhong et al.)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        arr = _as_np(img)
        if np.random.rand() >= self.prob:
            return arr
        h, w = arr.shape[0], arr.shape[1]
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh)
                j = np.random.randint(0, w - ew)
                v = (np.random.randn(eh, ew, *arr.shape[2:])
                     if self.value == "random" else self.value)
                return erase(arr, i, j, eh, ew, v, self.inplace)
        return arr
