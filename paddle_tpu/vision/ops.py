"""Detection ops: nms / roi_align / yolo_box / deform_conv2d.
Reference: python/paddle/vision/ops.py (:1934 nms, :1705 roi_align, :277 yolo_box,
:766 deform_conv2d). These are the PP-YOLOE dependency set; data-dependent-shape ops run
their selection logic on host (documented dynamic boundary, SURVEY.md §7.3.5)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import apply_op
from ..tensor import Tensor

__all__ = ["nms", "roi_align", "roi_pool", "box_coder", "yolo_box", "yolo_loss",
           "deform_conv2d", "DeformConv2D", "distribute_fpn_proposals",
           "generate_proposals", "PSRoIPool", "RoIAlign", "RoIPool",
           "read_file", "decode_jpeg", "prior_box", "matrix_nms"]


def _iou_matrix(boxes):
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    areas = (x2 - x1) * (y2 - y1)
    xx1 = np.maximum(x1[:, None], x1[None, :])
    yy1 = np.maximum(y1[:, None], y1[None, :])
    xx2 = np.minimum(x2[:, None], x2[None, :])
    yy2 = np.minimum(y2[:, None], y2[None, :])
    inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
    return inter / np.maximum(areas[:, None] + areas[None, :] - inter, 1e-10)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Reference ops.py:1934. Host-side greedy NMS (data-dependent output size)."""
    b = np.asarray(boxes._value if isinstance(boxes, Tensor) else boxes, np.float32)
    n = b.shape[0]
    if scores is None:
        order = np.arange(n)
    else:
        s = np.asarray(scores._value if isinstance(scores, Tensor) else scores)
        order = np.argsort(-s)
    if category_idxs is not None:
        cats = np.asarray(category_idxs._value if isinstance(category_idxs, Tensor)
                          else category_idxs)
        # offset boxes per category so cross-category boxes never suppress each other
        offset = (b.max() + 1.0) * cats.astype(np.float32)
        b = b + offset[:, None]
    iou = _iou_matrix(b)
    keep = []
    suppressed = np.zeros(n, bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        suppressed |= iou[i] > iou_threshold
        suppressed[i] = True  # self-suppress so it's not revisited; kept already
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0, sampling_ratio=-1,
              aligned=True, name=None):
    """Reference ops.py:1705. Bilinear-sampled ROI pooling — vectorized gather."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, rois, rois_num):
        # assign each roi its batch index from boxes_num
        n_rois = rois.shape[0]
        batch_idx = jnp.repeat(jnp.arange(rois_num.shape[0]), rois_num, axis=0,
                               total_repeat_length=n_rois)
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        sr = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid: [n_rois, oh*sr, ow*sr]
        ys = y1[:, None] + (jnp.arange(oh * sr) + 0.5) / (oh * sr) * rh[:, None]
        xs = x1[:, None] + (jnp.arange(ow * sr) + 0.5) / (ow * sr) * rw[:, None]
        H, W = feat.shape[2], feat.shape[3]

        def bilinear(fmap, yy, xx):
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            y1i = jnp.clip(y0.astype(jnp.int32), 0, H - 1)
            x1i = jnp.clip(x0.astype(jnp.int32), 0, W - 1)
            y2i = jnp.clip(y1i + 1, 0, H - 1)
            x2i = jnp.clip(x1i + 1, 0, W - 1)
            wy = yy - y0
            wx = xx - x0
            v11 = fmap[:, y1i, :][:, :, x1i]
            v12 = fmap[:, y1i, :][:, :, x2i]
            v21 = fmap[:, y2i, :][:, :, x1i]
            v22 = fmap[:, y2i, :][:, :, x2i]
            return (v11 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
                    + v12 * (1 - wy)[None, :, None] * wx[None, None, :]
                    + v21 * wy[None, :, None] * (1 - wx)[None, None, :]
                    + v22 * wy[None, :, None] * wx[None, None, :])

        def per_roi(bi, yy, xx):
            fmap = feat[bi]  # [C,H,W]
            sampled = bilinear(fmap, yy, xx)  # [C, oh*sr, ow*sr]
            C = sampled.shape[0]
            pooled = sampled.reshape(C, oh, sr, ow, sr).mean(axis=(2, 4))
            return pooled

        return jax.vmap(per_roi)(batch_idx, ys, xs)

    return apply_op(f, "roi_align", x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    return roi_align(x, boxes, boxes_num, output_size, spatial_scale, sampling_ratio=1,
                     aligned=False)


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self.output_size, self.spatial_scale,
                         aligned=aligned)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive ROI pooling (reference ops.py psroi_pool / R-FCN).

    x: [N, C, H, W] with C = out_channels * oh * ow; output bin (i, j) of each
    ROI average-pools the spatial region of the bin FROM the channel group
    dedicated to that bin position.
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, rois, rois_num):
        n_rois = rois.shape[0]
        C = feat.shape[1]
        out_c = C // (oh * ow)
        H, W = feat.shape[2], feat.shape[3]
        batch_idx = jnp.repeat(jnp.arange(rois_num.shape[0]), rois_num, axis=0,
                               total_repeat_length=n_rois)
        x1 = rois[:, 0] * spatial_scale
        y1 = rois[:, 1] * spatial_scale
        x2 = rois[:, 2] * spatial_scale
        y2 = rois[:, 3] * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        # sample each bin on a fixed sub-grid (TPU-friendly static shapes)
        sr = 2
        ys = y1[:, None] + (jnp.arange(oh * sr) + 0.5) / (oh * sr) * rh[:, None]
        xs = x1[:, None] + (jnp.arange(ow * sr) + 0.5) / (ow * sr) * rw[:, None]
        yi = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, H - 1)
        xi = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, W - 1)

        # each output bin (i, j) samples ONLY its own channel group and its own
        # sr×sr sample sub-grid — a double vmap over bins, no (bin, sample-bin)
        # cross product is materialized
        def per_roi(bi, yrow, xrow):
            g = feat[bi].reshape(out_c, oh, ow, H, W)
            yb = yrow.reshape(oh, sr)
            xb = xrow.reshape(ow, sr)

            def per_bin(i, j):
                patch = g[:, i, j]               # [out_c, H, W]
                vals = patch[:, yb[i], :][:, :, xb[j]]  # [out_c, sr, sr]
                return vals.mean(axis=(1, 2))

            grid = jax.vmap(lambda i: jax.vmap(lambda j: per_bin(i, j))(
                jnp.arange(ow)))(jnp.arange(oh))  # [oh, ow, out_c]
            return jnp.transpose(grid, (2, 0, 1))

        return jax.vmap(per_roi)(batch_idx, yi, xi)

    return apply_op(f, "psroi_pool", x, boxes, boxes_num)


class PSRoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    def f(pb, pbv, tb):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            dx = (tcx - pcx) / pw
            dy = (tcy - pcy) / ph
            dw = jnp.log(tw / pw)
            dh = jnp.log(th / ph)
            out = jnp.stack([dx, dy, dw, dh], axis=-1)
            if pbv is not None:
                out = out / pbv
            return out
        # decode_center_size
        d = tb
        if pbv is not None:
            d = d * pbv
        if d.ndim == 2:
            d = d[:, None, :]
        cx = d[..., 0] * pw[:, None] + pcx[:, None]
        cy = d[..., 1] * ph[:, None] + pcy[:, None]
        w = jnp.exp(d[..., 2]) * pw[:, None]
        h = jnp.exp(d[..., 3]) * ph[:, None]
        return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2 - norm, cy + h / 2 - norm],
                         axis=-1).squeeze()

    return apply_op(f, "box_coder", prior_box, prior_box_var, target_box)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """Reference ops.py:277 — decode YOLO head output into boxes+scores."""

    def f(xv, imgs):
        n, c, h, w = xv.shape
        na = len(anchors) // 2
        an = jnp.asarray(np.asarray(anchors, np.float32).reshape(na, 2))
        pred = xv.reshape(n, na, -1, h, w)
        gx = jnp.arange(w, dtype=jnp.float32)
        gy = jnp.arange(h, dtype=jnp.float32)
        cx = (jax.nn.sigmoid(pred[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2 +
              gx[None, None, None, :]) / w
        cy = (jax.nn.sigmoid(pred[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2 +
              gy[None, None, :, None]) / h
        bw = jnp.exp(pred[:, :, 2]) * an[None, :, 0, None, None] / (w * downsample_ratio)
        bh = jnp.exp(pred[:, :, 3]) * an[None, :, 1, None, None] / (h * downsample_ratio)
        obj = jax.nn.sigmoid(pred[:, :, 4])
        cls = jax.nn.sigmoid(pred[:, :, 5:5 + class_num])
        obj = jnp.where(obj < conf_thresh, 0.0, obj)
        imgh = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        imgw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (cx - bw / 2) * imgw
        y1 = (cy - bh / 2) * imgh
        x2 = (cx + bw / 2) * imgw
        y2 = (cy + bh / 2) * imgh
        if clip_bbox:
            x1 = jnp.clip(x1, 0)
            y1 = jnp.clip(y1, 0)
            x2 = jnp.minimum(x2, imgw - 1)
            y2 = jnp.minimum(y2, imgh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
        scores = (obj[:, :, None] * cls).transpose(0, 1, 3, 4, 2).reshape(
            n, -1, class_num)
        return boxes, scores

    return apply_op(f, "yolo_box", x, img_size)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference ops.py yolo_loss semantics).

    x: [N, mask*(5+classes), H, W] head output. gt_box: [N, B, 4] in
    (cx, cy, w, h), normalized to the input image. gt_label: [N, B] int.
    Returns per-image loss [N]. Matching follows YOLOv3: a gt is assigned to
    the anchor (across ALL anchors) with best IoU at the gt's cell; predictions
    whose best-gt IoU exceeds ignore_thresh are excluded from the no-object
    objectness loss.
    """
    na_all = len(anchors) // 2
    mask = list(anchor_mask)
    nm = len(mask)

    def f(xv, gb, gl, gs):
        n, c, h, w = xv.shape
        an_all = jnp.asarray(np.asarray(anchors, np.float32).reshape(na_all, 2))
        an = an_all[jnp.asarray(mask)]
        pred = xv.reshape(n, nm, 5 + class_num, h, w)
        tx, ty = pred[:, :, 0], pred[:, :, 1]
        tw, th = pred[:, :, 2], pred[:, :, 3]
        tobj = pred[:, :, 4]
        tcls = pred[:, :, 5:]

        stride = downsample_ratio
        in_w, in_h = w * stride, h * stride
        nb = gb.shape[1]
        valid = (gb[:, :, 2] > 0) & (gb[:, :, 3] > 0)          # [N, B]

        # --- anchor assignment: best-IoU anchor (shape-only, centered)
        gw = gb[:, :, 2] * in_w
        gh = gb[:, :, 3] * in_h
        inter = (jnp.minimum(gw[..., None], an_all[None, None, :, 0])
                 * jnp.minimum(gh[..., None], an_all[None, None, :, 1]))
        union = gw[..., None] * gh[..., None] + an_all[None, None, :, 0] * \
            an_all[None, None, :, 1] - inter
        best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)  # [N,B]
        on_level = jnp.zeros_like(best_anchor, bool)
        for li, a in enumerate(mask):
            on_level = on_level | (best_anchor == a)
        level_idx = jnp.zeros_like(best_anchor)
        for li, a in enumerate(mask):
            level_idx = jnp.where(best_anchor == a, li, level_idx)
        assign = valid & on_level                              # [N, B]

        gi = jnp.clip((gb[:, :, 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gb[:, :, 1] * h).astype(jnp.int32), 0, h - 1)

        # targets in head space
        txt = gb[:, :, 0] * w - gi
        tyt = gb[:, :, 1] * h - gj
        twt = jnp.log(jnp.maximum(gw / jnp.maximum(an[level_idx][..., 0], 1e-9),
                                  1e-9))
        tht = jnp.log(jnp.maximum(gh / jnp.maximum(an[level_idx][..., 1], 1e-9),
                                  1e-9))
        box_scale = 2.0 - gb[:, :, 2] * gb[:, :, 3]            # small-box upweight
        score = gs if gs is not None else jnp.ones_like(txt)

        # scatter gt info onto the [N, nm, h, w] grid. Collisions (two gts in
        # the same cell/level) OVERWRITE — last writer wins like the reference's
        # per-gt loop — never sum, which would fabricate out-of-range targets.
        bidx_all = jnp.arange(n)[:, None] * jnp.ones((1, nb), jnp.int32)
        flat_all = ((bidx_all * nm + level_idx) * h + gj) * w + gi
        sink = n * nm * h * w  # unassigned gts scatter off the end (dropped)
        flat_assigned = jnp.where(assign, flat_all, sink)

        def scatter(vals):
            out = jnp.zeros((n * nm * h * w,), vals.dtype)
            out = out.at[flat_assigned.reshape(-1)].set(
                vals.reshape(-1), mode="drop")
            return out.reshape(n, nm, h, w)

        obj_mask = scatter(jnp.ones_like(txt)) > 0
        sc = scatter(score * box_scale)
        # scale_x_y (PP-YOLO grid-sensitive decode): the head emits
        # sigmoid(t)*s - (s-1)/2, so the BCE target for sigmoid(t) is
        # (frac + (s-1)/2) / s
        sxy = float(scale_x_y)
        txt = (txt + (sxy - 1) / 2) / sxy
        tyt = (tyt + (sxy - 1) / 2) / sxy
        bce = lambda logit, t: jnp.maximum(logit, 0) - logit * t + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

        loss_xy = (bce(tx, scatter(txt)) + bce(ty, scatter(tyt))) * sc
        loss_wh = ((tw - scatter(twt)) ** 2 + (th - scatter(tht)) ** 2) * 0.5 * sc

        # ignore mask: prediction boxes with IoU > thresh vs any gt
        gxg = jnp.arange(w, dtype=jnp.float32)
        gyg = jnp.arange(h, dtype=jnp.float32)
        px = (jax.nn.sigmoid(tx) * sxy - (sxy - 1) / 2
              + gxg[None, None, None, :]) / w
        py = (jax.nn.sigmoid(ty) * sxy - (sxy - 1) / 2
              + gyg[None, None, :, None]) / h
        pw = jnp.exp(tw) * an[None, :, 0, None, None] / in_w
        ph = jnp.exp(th) * an[None, :, 1, None, None] / in_h
        p1x, p1y = px - pw / 2, py - ph / 2
        p2x, p2y = px + pw / 2, py + ph / 2
        g1x = gb[:, :, 0] - gb[:, :, 2] / 2
        g1y = gb[:, :, 1] - gb[:, :, 3] / 2
        g2x = gb[:, :, 0] + gb[:, :, 2] / 2
        g2y = gb[:, :, 1] + gb[:, :, 3] / 2

        def iou_vs_gts(p1x_, p1y_, p2x_, p2y_):
            ix = jnp.maximum(
                jnp.minimum(p2x_[..., None], g2x[:, None, None, None, :])
                - jnp.maximum(p1x_[..., None], g1x[:, None, None, None, :]), 0)
            iy = jnp.maximum(
                jnp.minimum(p2y_[..., None], g2y[:, None, None, None, :])
                - jnp.maximum(p1y_[..., None], g1y[:, None, None, None, :]), 0)
            inter_ = ix * iy
            pa = (p2x_ - p1x_) * (p2y_ - p1y_)
            ga = ((g2x - g1x) * (g2y - g1y))[:, None, None, None, :]
            iou = inter_ / jnp.maximum(pa[..., None] + ga - inter_, 1e-9)
            return jnp.max(jnp.where(valid[:, None, None, None, :], iou, 0.0),
                           axis=-1)

        best_iou = iou_vs_gts(p1x, p1y, p2x, p2y)
        noobj = (~obj_mask) & (best_iou < ignore_thresh)
        loss_obj = bce(tobj, obj_mask.astype(tobj.dtype)) * jnp.where(
            obj_mask, sc, noobj.astype(tobj.dtype))

        smooth = 1.0 / class_num if use_label_smooth and class_num > 1 else 0.0
        onehot = jax.nn.one_hot(jnp.where(assign, gl, 0), class_num)
        onehot = onehot * (1 - smooth) + smooth / class_num
        cls_t = scatter_cls(onehot, flat_assigned, n, nm, h, w, class_num)
        loss_cls = (bce(tcls, cls_t)
                    * obj_mask[:, :, None].astype(tcls.dtype)).sum(2)

        total = (loss_xy + loss_wh + loss_obj + loss_cls)
        return total.reshape(n, -1).sum(-1)

    def scatter_cls(onehot, flat_assigned, n, nm, h, w, ncls):
        # overwrite, not add: collisions keep ONE box's class row (see scatter)
        out = jnp.zeros((n * nm * h * w, ncls), onehot.dtype)
        out = out.at[flat_assigned.reshape(-1)].set(
            onehot.reshape(-1, ncls), mode="drop")
        return out.reshape(n, nm, h, w, ncls).transpose(0, 1, 4, 2, 3)

    args = [x, gt_box, gt_label]
    args.append(gt_score if gt_score is not None else None)
    return apply_op(f, "yolo_loss", *args)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    """Reference ops.py:766 (DCNv1/v2). Gather-based implementation: sample input at
    offset positions then 1x1-matmul with the kernel — maps to gathers + one MXU matmul."""
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation

    def f(xv, off, w, b, m):
        n, cin, H, W = xv.shape
        cout, cin_g, kh, kw = w.shape
        oh = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        ow = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        xp = jnp.pad(xv, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        Hp, Wp = xp.shape[2], xp.shape[3]
        # offsets: [N, 2*dg*kh*kw, oh, ow]
        off = off.reshape(n, deformable_groups, 2, kh * kw, oh, ow)
        oy = off[:, :, 0].reshape(n, deformable_groups, kh, kw, oh, ow)
        ox = off[:, :, 1].reshape(n, deformable_groups, kh, kw, oh, ow)
        # sample positions per output pixel & kernel tap
        yy = (jnp.arange(oh) * sh)[None, None, None, None, :, None] + \
             (jnp.arange(kh) * dh)[None, None, :, None, None, None] + oy
        xx = (jnp.arange(ow) * sw)[None, None, None, None, None, :] + \
             (jnp.arange(kw) * dw)[None, None, None, :, None, None] + ox
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        wy = yy - y0
        wx = xx - x0

        def gather_at(yi, xi):
            yi = jnp.clip(yi.astype(jnp.int32), 0, Hp - 1)
            xi = jnp.clip(xi.astype(jnp.int32), 0, Wp - 1)
            flat = xp.reshape(n, cin, -1)
            lin = yi * Wp + xi  # [n, dg, kh, kw, oh, ow]
            cg = cin // deformable_groups
            out = []
            for g in range(deformable_groups):
                idx = lin[:, g].reshape(n, -1)
                vals = jnp.take_along_axis(
                    flat[:, g * cg:(g + 1) * cg], idx[:, None, :], axis=2
                )
                out.append(vals.reshape(n, cg, kh, kw, oh, ow))
            return jnp.concatenate(out, axis=1)

        w11 = (1 - wy) * (1 - wx)
        w12 = (1 - wy) * wx
        w21 = wy * (1 - wx)
        w22 = wy * wx

        def expand_w(wv):
            return jnp.repeat(wv, cin // deformable_groups, axis=1)

        sampled = (gather_at(y0, x0) * expand_w(w11) + gather_at(y0, x0 + 1) * expand_w(w12)
                   + gather_at(y0 + 1, x0) * expand_w(w21)
                   + gather_at(y0 + 1, x0 + 1) * expand_w(w22))
        if m is not None:
            mm = m.reshape(n, deformable_groups, kh, kw, oh, ow)
            sampled = sampled * expand_w(mm)
        # contract: out[n,co,oh,ow] = sum_{ci,kh,kw} sampled * w (one MXU matmul)
        if groups == 1:
            out = jnp.einsum("nckhij,ockh->noij",
                             sampled.reshape(n, cin, kh, kw, oh, ow), w)
        else:
            cg_in = cin // groups
            cg_out = cout // groups
            outs = []
            for g in range(groups):
                outs.append(jnp.einsum(
                    "nckhij,ockh->noij",
                    sampled.reshape(n, cin, kh, kw, oh, ow)[:, g * cg_in:(g + 1) * cg_in],
                    w[g * cg_out:(g + 1) * cg_out],
                ))
            out = jnp.concatenate(outs, axis=1)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out

    return apply_op(f, "deform_conv2d", x, offset, weight, bias, mask)


class DeformConv2D:
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, deformable_groups=1, groups=1, weight_attr=None,
                 bias_attr=None):
        from ..nn.layer_conv_norm import Conv2D as _C

        helper = _C(in_channels, out_channels, kernel_size, stride=stride,
                    padding=padding, dilation=dilation, groups=groups,
                    weight_attr=weight_attr, bias_attr=bias_attr)
        self.weight = helper.weight
        self.bias = helper.bias
        self._args = (stride, padding, dilation, deformable_groups, groups)

    def __call__(self, x, offset, mask=None):
        s, p, d, dg, g = self._args
        return deform_conv2d(x, offset, self.weight, self.bias, s, p, d, dg, g, mask)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level, refer_scale,
                             pixel_offset=False, rois_num=None, name=None):
    """Reference ops.py:1175 — host-side level assignment (dynamic shapes)."""
    rois = np.asarray(fpn_rois._value)
    offset = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + offset
    hs = rois[:, 3] - rois[:, 1] + offset
    scale = np.sqrt(ws * hs)
    levels = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    levels = np.clip(levels, min_level, max_level).astype(np.int64)
    multi_rois = []
    restore_parts = []
    rois_num_per = []
    for lvl in range(min_level, max_level + 1):
        idx = np.where(levels == lvl)[0]
        multi_rois.append(Tensor(jnp.asarray(rois[idx])))
        rois_num_per.append(Tensor(jnp.asarray(np.asarray([len(idx)], np.int32))))
        restore_parts.append(idx)
    order = np.concatenate(restore_parts) if restore_parts else np.zeros(0, np.int64)
    restore = np.argsort(order).astype(np.int32)
    return multi_rois, Tensor(jnp.asarray(restore[:, None])), rois_num_per


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000, nms_thresh=0.5,
                       min_size=0.1, eta=1.0, pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference ops.py generate_proposals / RPNHead).

    scores: [N, A, H, W]; bbox_deltas: [N, 4*A, H, W]; anchors: [H*W*A, 4]
    (x1,y1,x2,y2); variances: [H*W*A, 4]. Decode deltas onto anchors, clip to
    the image, drop boxes under min_size, take pre_nms_top_n by score, NMS,
    keep post_nms_top_n. Device decodes/filters (static shapes); the final
    greedy NMS is host-side like `nms` above (data-dependent output size).
    """
    import jax.numpy as _jnp

    def decode(sc, bd, imsz, anc, var):
        n, a, h, w = sc.shape
        sc_flat = sc.transpose(0, 2, 3, 1).reshape(n, -1)          # [N, HWA]
        bd_flat = bd.reshape(n, a, 4, h, w).transpose(0, 3, 4, 1, 2).reshape(n, -1, 4)
        anc = anc.reshape(-1, 4)
        var = var.reshape(-1, 4)
        aw = anc[:, 2] - anc[:, 0] + (1.0 if pixel_offset else 0.0)
        ah = anc[:, 3] - anc[:, 1] + (1.0 if pixel_offset else 0.0)
        acx = anc[:, 0] + aw * 0.5
        acy = anc[:, 1] + ah * 0.5
        dx = bd_flat[..., 0] * var[None, :, 0]
        dy = bd_flat[..., 1] * var[None, :, 1]
        dw = _jnp.clip(bd_flat[..., 2] * var[None, :, 2], -10.0, 4.135)
        dh = _jnp.clip(bd_flat[..., 3] * var[None, :, 3], -10.0, 4.135)
        cx = dx * aw[None] + acx[None]
        cy = dy * ah[None] + acy[None]
        bw = _jnp.exp(dw) * aw[None]
        bh = _jnp.exp(dh) * ah[None]
        off = 1.0 if pixel_offset else 0.0
        x1 = cx - bw * 0.5
        y1 = cy - bh * 0.5
        x2 = cx + bw * 0.5 - off
        y2 = cy + bh * 0.5 - off
        imh = imsz[:, 0].astype(_jnp.float32)[:, None]
        imw = imsz[:, 1].astype(_jnp.float32)[:, None]
        x1 = _jnp.clip(x1, 0.0, None)
        y1 = _jnp.clip(y1, 0.0, None)
        x2 = _jnp.minimum(x2, imw - off)
        y2 = _jnp.minimum(y2, imh - off)
        keepable = ((x2 - x1 + off) >= min_size) & ((y2 - y1 + off) >= min_size)
        sc_flat = _jnp.where(keepable, sc_flat, -_jnp.inf)
        k = min(pre_nms_top_n, sc_flat.shape[1])
        top_s, top_i = jax.lax.top_k(sc_flat, k)
        boxes = _jnp.stack([x1, y1, x2, y2], -1)
        top_b = _jnp.take_along_axis(boxes, top_i[..., None], axis=1)
        return top_b, top_s

    top_b, top_s = apply_op(decode, "generate_proposals_decode",
                            scores, bbox_deltas, img_size,
                            Tensor(jnp.asarray(np.asarray(
                                anchors._value if isinstance(anchors, Tensor)
                                else anchors))),
                            Tensor(jnp.asarray(np.asarray(
                                variances._value if isinstance(variances, Tensor)
                                else variances))), nout=2)

    # host-side NMS per image (greedy, data-dependent)
    all_rois, all_scores, rois_num = [], [], []
    b_np = np.asarray(top_b._value)
    s_np = np.asarray(top_s._value)
    for i in range(b_np.shape[0]):
        ok = np.isfinite(s_np[i])
        bi, si = b_np[i][ok], s_np[i][ok]
        keep = np.asarray(nms(Tensor(jnp.asarray(bi)), nms_thresh,
                              scores=Tensor(jnp.asarray(si)))._value)
        keep = keep[:post_nms_top_n]
        all_rois.append(bi[keep])
        all_scores.append(si[keep])
        rois_num.append(len(keep))
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0) if all_rois
                              else np.zeros((0, 4), np.float32)))
    # scores aligned 1:1 with rois (reference rpn_roi_probs contract)
    scores_out = Tensor(jnp.asarray(
        np.concatenate(all_scores, 0) if all_scores
        else np.zeros((0,), np.float32)))
    nums = Tensor(jnp.asarray(np.asarray(rois_num, np.int32)))
    if return_rois_num:
        return rois, scores_out, nums
    return rois, scores_out


def read_file(filename, name=None):
    """Reference: vision/ops.py read_file — raw file bytes as a uint8 tensor."""
    import jax.numpy as jnp

    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Reference: vision/ops.py decode_jpeg (nvjpeg kernel) — host-side PIL
    decode (image io is input-pipeline work), returns CHW uint8."""
    import io as _io

    import jax.numpy as jnp
    from PIL import Image

    raw = bytes(np.asarray(x._value if isinstance(x, Tensor) else x,
                           np.uint8))
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb", "RGB"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """Reference: vision/ops.py prior_box — SSD prior boxes over the feature
    map grid (host math mirrored from the CUDA kernel's enumeration order)."""
    import jax.numpy as jnp

    fh, fw = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for y in range(fh):
        for x in range(fw):
            cx = (x + offset) * step_w
            cy = (y + offset) * step_h
            cell = []
            for k, ms in enumerate(min_sizes):
                ms = float(ms)
                if min_max_aspect_ratios_order:
                    cell.append((cx, cy, ms, ms))
                    if max_sizes:
                        big = np.sqrt(ms * float(max_sizes[k]))
                        cell.append((cx, cy, big, big))
                    for ar in ars:
                        if abs(ar - 1.0) < 1e-6:
                            continue
                        r = np.sqrt(ar)
                        cell.append((cx, cy, ms * r, ms / r))
                else:
                    for ar in ars:
                        r = np.sqrt(ar)
                        cell.append((cx, cy, ms * r, ms / r))
                    if max_sizes:
                        big = np.sqrt(ms * float(max_sizes[k]))
                        cell.append((cx, cy, big, big))
            boxes.extend(cell)
    b = np.asarray(boxes, np.float32)
    out = np.stack([
        (b[:, 0] - b[:, 2] / 2) / iw, (b[:, 1] - b[:, 3] / 2) / ih,
        (b[:, 0] + b[:, 2] / 2) / iw, (b[:, 1] + b[:, 3] / 2) / ih,
    ], 1).reshape(fh, fw, -1, 4)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Reference: vision/ops.py matrix_nms (SOLOv2) — parallel soft-NMS:
    scores decayed by max-IoU against higher-scored peers, no sequential
    suppression loop."""
    import jax.numpy as jnp

    bv = np.asarray(bboxes._value if isinstance(bboxes, Tensor) else bboxes)
    sv = np.asarray(scores._value if isinstance(scores, Tensor) else scores)
    outs, indices, nums = [], [], []
    offset = 0.0 if normalized else 1.0
    for b in range(bv.shape[0]):
        dets, idxs = [], []
        for c in range(sv.shape[1]):
            if c == background_label:
                continue
            s = sv[b, c]
            keep = np.where(s > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-s[keep])][:nms_top_k]
            boxes = bv[b, order]
            ss = s[order]
            x1, y1, x2, y2 = boxes.T
            area = (x2 - x1 + offset) * (y2 - y1 + offset)
            n = len(order)
            xx1 = np.maximum(x1[:, None], x1[None, :])
            yy1 = np.maximum(y1[:, None], y1[None, :])
            xx2 = np.minimum(x2[:, None], x2[None, :])
            yy2 = np.minimum(y2[:, None], y2[None, :])
            inter = (np.clip(xx2 - xx1 + offset, 0, None)
                     * np.clip(yy2 - yy1 + offset, 0, None))
            iou = inter / (area[:, None] + area[None, :] - inter)
            # iou[j, i] for j < i = overlap of det i with the better det j
            iou = np.triu(iou, 1)
            # compensate_j = worst overlap det j itself suffered from ITS
            # betters (column max); decay_i = min over j<i of
            # f(iou_ji)/f(compensate_j)  (SOLOv2 matrix NMS)
            comp = iou.max(axis=0)
            if use_gaussian:
                decay_mat = np.exp(-(iou ** 2 - comp[:, None] ** 2)
                                   / gaussian_sigma)
            else:
                decay_mat = (1 - iou) / np.clip(1 - comp[:, None], 1e-6, None)
            # only j < i entries participate; pad the rest with +inf so the
            # column min ignores them (det 0 keeps decay 1.0)
            decay_mat = np.where(np.triu(np.ones((n, n), bool), 1), decay_mat,
                                 np.inf)
            decay = np.minimum(decay_mat.min(axis=0), 1.0)
            new_s = ss * decay
            for i in range(n):
                if new_s[i] > post_threshold:
                    dets.append([c, new_s[i], *boxes[i]])
                    idxs.append(order[i])
        if dets:
            dets = np.asarray(dets, np.float32)
            order = np.argsort(-dets[:, 1])[:keep_top_k]
            dets = dets[order]
            idxs = np.asarray(idxs)[order]
        else:
            dets = np.zeros((0, 6), np.float32)
            idxs = np.zeros((0,), np.int64)
        outs.append(dets)
        indices.append(idxs + b * sv.shape[2] if idxs.size else idxs)
        nums.append(len(dets))
    out = Tensor(jnp.asarray(np.concatenate(outs) if outs else
                             np.zeros((0, 6), np.float32)))
    rois_num = Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    index = Tensor(jnp.asarray(np.concatenate(indices).astype(np.int64)
                               if indices else np.zeros((0,), np.int64)))
    result = [out]
    if return_index:
        result.append(index)
    if return_rois_num:
        result.append(rois_num)
    return tuple(result) if len(result) > 1 else out
