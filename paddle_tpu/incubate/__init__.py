"""paddle.incubate staging ground. Reference: python/paddle/incubate/."""
from . import distributed  # noqa: F401
from . import nn  # noqa: F401

from . import asp  # noqa: E402,F401
from ._tail import (  # noqa: E402,F401
    LookAhead, ModelAverage, graph_khop_sampler, graph_reindex,
    graph_sample_neighbors, graph_send_recv, identity_loss, segment_max,
    segment_mean, segment_min, segment_sum, softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)
# reference __all__ lists `inference` (incubate/inference decorator module);
# the deployable-inference surface here is paddle.inference — alias the
# namespace so incubate.inference resolves
from .. import inference  # noqa: E402,F401
