"""paddle.incubate staging ground. Reference: python/paddle/incubate/."""
from . import distributed  # noqa: F401
from . import nn  # noqa: F401

from . import asp  # noqa: E402,F401
