"""paddle.incubate staging ground. Reference: python/paddle/incubate/."""
from . import nn  # noqa: F401
