"""Memory-efficient attention. Reference:
python/paddle/incubate/nn/memory_efficient_attention.py (xformers-style
cutlass kernel wrapper).

TPU-native: the role is filled by the Pallas flash-attention kernel (same
O(S) memory property); this wrapper adds the reference's attn_bias / scale /
dropout surface on the paddle [B, S, H, D] layout and falls back to a fused
bias-aware einsum path when a bias tensor rules the flash kernel out."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...ops import apply_op
from ...tensor import Tensor

__all__ = ["memory_efficient_attention"]


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True):
    """query/key/value: [B, S, H, D] (paddle layout). attn_bias: broadcastable
    to [B, H, Sq, Sk] or the string 'causal'. Returns [B, S, H, D]."""
    from ...nn import functional as F

    causal = isinstance(attn_bias, str) and attn_bias.lower() == "causal"
    if causal or attn_bias is None:
        q = query
        if scale is not None:
            # flash kernel bakes in 1/sqrt(d): pre-scale the query once
            ratio = scale * math.sqrt(query.shape[-1])
            if abs(ratio - 1.0) > 1e-9:
                q = query * ratio
        out, _ = F.flash_attention(
            q, key, value, dropout=p if training else 0.0,
            causal=causal, training=training)
        return out

    def f(q, k, v, bias):
        d = q.shape[-1]
        s = scale if scale is not None else 1.0 / math.sqrt(d)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * s
        logits = logits + bias.astype(logits.dtype)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        if p and training:
            from ...framework import random as _rng

            keep = 1.0 - p
            mask = jax.random.bernoulli(_rng.next_key(), keep, probs.shape)
            probs = jnp.where(mask, probs / keep, 0.0)
        return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)

    return apply_op(f, "memory_efficient_attention", query, key, value,
                    attn_bias)
