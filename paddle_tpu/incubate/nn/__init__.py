"""Fused transformer blocks. Reference: python/paddle/incubate/nn/layer/
fused_transformer.py:213,534,750. On TPU "fused" means: written so XLA emits one fused
region — same API, compiler does the fusion."""
from .fused_transformer import (  # noqa: F401
    FusedFeedForward,
    FusedMultiHeadAttention,
    FusedTransformerEncoderLayer,
)
from . import functional  # noqa: F401

from .memory_efficient_attention import (  # noqa: E402,F401
    memory_efficient_attention,
)
