"""incubate.nn.functional: fused functional ops (API parity; XLA does the fusing).
Reference: python/paddle/incubate/nn/functional/."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ....ops import apply_op

__all__ = ["fused_linear", "fused_bias_act", "fused_rotary_position_embedding",
           "fused_rms_norm", "fused_layer_norm", "swiglu", "fused_dropout_add",
           "fused_multi_head_attention", "fused_feedforward"]


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def f(v, w, b):
        if transpose_weight:
            w = w.T
        out = v @ w
        return out + b if b is not None else out

    return apply_op(f, "fused_linear", x, weight, bias)


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None, smooth=None,
                   act_method="gelu", compute_dtype="default", quant_scale=-1,
                   quant_round_type=0, quant_max_bound=0, quant_min_bound=0):
    def f(v, b):
        if b is not None:
            v = v + b
        if act_method == "gelu":
            return jax.nn.gelu(v)
        if act_method in ("swiglu",):
            a, g = jnp.split(v, 2, axis=-1)
            return jax.nn.silu(a) * g
        return getattr(jax.nn, act_method)(v)

    return apply_op(f, "fused_bias_act", x, bias)


def swiglu(x, y=None, name=None):
    if y is not None:
        return apply_op(lambda a, b: jax.nn.silu(a) * b, "swiglu", x, y)

    def f(v):
        a, b = jnp.split(v, 2, axis=-1)
        return jax.nn.silu(a) * b

    return apply_op(f, "swiglu", x)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """Reference: fused_rope (paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu).
    Layout [batch, seq, heads, head_dim]."""

    def rope_one(t, sin_v, cos_v):
        if t is None:
            return None
        if use_neox_rotary_style:
            half = t.shape[-1] // 2
            t1 = t[..., :half]
            t2 = t[..., half:]
            rot = jnp.concatenate([-t2, t1], axis=-1)
        else:
            t1 = t[..., 0::2]
            t2 = t[..., 1::2]
            rot = jnp.stack([-t2, t1], axis=-1).reshape(t.shape)
        return t * cos_v + rot * sin_v

    def f(qv, kv, vv, sin_v, cos_v, pos):
        S = qv.shape[1]
        D = qv.shape[-1]
        pos_applied = False
        if sin_v is None:
            inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
            if pos is not None:
                # absolute positions (KV-cache decode): build frequencies for
                # exactly these positions — a table of only S rows indexed by
                # absolute position would clip/misrotate past the first step.
                # A [B, S] pos builds PER-ROW frequencies (left-padded batches).
                pos_seq = pos.astype(jnp.float32)
                pos_applied = True
            else:
                pos_seq = jnp.arange(S, dtype=jnp.float32)
            freqs = pos_seq[..., None] * inv  # [S, D/2] or [B, S, D/2]
            if use_neox_rotary_style:
                emb = jnp.concatenate([freqs, freqs], axis=-1)
            else:
                emb = jnp.repeat(freqs, 2, axis=-1)
            if emb.ndim == 2:       # [S, D] → [1, S, 1, D]
                emb = emb[None, :, None, :]
            else:                   # [B, S, D] → [B, S, 1, D]
                emb = emb[:, :, None, :]
            sin_v = jnp.sin(emb)
            cos_v = jnp.cos(emb)
        else:
            if sin_v.ndim == 2:
                sin_v = sin_v[None, :, None, :]
                cos_v = cos_v[None, :, None, :]
            elif sin_v.ndim == 4 and sin_v.shape[2] != 1:
                pass
        if pos is not None and not pos_applied:
            sin_v = jnp.take(sin_v[0, :, 0], pos.astype(jnp.int32), axis=0)[:, :, None, :]
            cos_v = jnp.take(cos_v[0, :, 0], pos.astype(jnp.int32), axis=0)[:, :, None, :]
        sin_v = sin_v.astype(qv.dtype)
        cos_v = cos_v.astype(qv.dtype)
        outs = tuple(rope_one(t, sin_v, cos_v) for t in (qv, kv, vv) if t is not None)
        n_none = sum(t is None for t in (qv, kv, vv))
        full = []
        it = iter(outs)
        for t in (qv, kv, vv):
            full.append(None if t is None else next(it))
        return tuple(x for x in full if x is not None) if len(outs) > 1 else outs[0]

    out = apply_op(f, "fused_rope", q, k, v, sin, cos, position_ids)
    if isinstance(out, tuple):
        res = list(out)
        while len(res) < 3:
            res.append(None)
        return tuple(res[:3])
    return out, None, None


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1,
                   bias=None, residual=None, quant_scale=-1, quant_round_type=0,
                   quant_max_bound=0, quant_min_bound=0):
    def f(v, w, b, extra_bias, res):
        if extra_bias is not None:
            v = v + extra_bias
        if res is not None:
            v = v + res
        ms = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (v.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)).astype(v.dtype)
        out = out * w
        if b is not None:
            out = out + b
        return out

    return apply_op(f, "fused_rms_norm", x, norm_weight, norm_bias, bias, residual)


def fused_layer_norm(x, norm_weight, norm_bias=None, epsilon=1e-5, begin_norm_axis=-1,
                     bias=None, residual=None, **kw):
    def f(v, w, b, extra_bias, res):
        if extra_bias is not None:
            v = v + extra_bias
        if res is not None:
            v = v + res
        mean = jnp.mean(v, axis=-1, keepdims=True)
        var = jnp.var(v, axis=-1, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + epsilon)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return out

    return apply_op(f, "fused_layer_norm", x, norm_weight, norm_bias, bias, residual)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """dropout(x) + y in one op (reference incubate fused_dropout_add).
    XLA fuses the mask+scale+add into one elementwise kernel."""
    from ....framework import random as _rng

    def f(xv, yv):
        if not training or p == 0.0:
            return xv + yv
        keep = jax.random.bernoulli(_rng.next_key(), 1.0 - p, xv.shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, xv / (1.0 - p), 0.0).astype(xv.dtype) + yv
        return jnp.where(keep, xv, 0.0).astype(xv.dtype) + yv

    return apply_op(f, "fused_dropout_add", x, y)


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None, attn_mask=None,
                               dropout_rate=0.0, attn_dropout_rate=0.0,
                               ln_epsilon=1e-5, training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=None,
                               transpose_qkv_wb=False, name=None):
    """Whole MHA block in one traced op (reference incubate
    fused_multi_head_attention): [pre-LN ->] qkv -> sdpa attention (shared
    _sdpa_core: mask + attention dropout) -> out-proj -> hidden dropout ->
    [residual ->] [post-LN]. XLA fuses the epilogues.

    qkv_weight: [3, num_heads, head_dim, embed] (paddle layout) or, with
    transpose_qkv_wb, [embed, 3*embed]."""
    if cache_kv is not None:
        raise NotImplementedError(
            "cache_kv incremental decode is not supported here; use "
            "GPTForCausalLM.generate-style per-layer caches")
    from ....framework import random as _rng
    from ....nn.functional.flash_attention import _sdpa_core

    def f(xv, qkv_w, qkv_b, lin_w, lin_b, pre_s, pre_b, post_s, post_b, mask):
        B, S, E = xv.shape
        residual = xv
        h = xv
        if pre_layer_norm:
            mean = jnp.mean(h, axis=-1, keepdims=True)
            var = jnp.var(h, axis=-1, keepdims=True)
            h = (h - mean) * jax.lax.rsqrt(var + pre_ln_epsilon)
            if pre_s is not None:
                h = h * pre_s
            if pre_b is not None:
                h = h + pre_b
        if transpose_qkv_wb:
            nh = num_heads
            hd = E // nh
            qkv = h @ qkv_w  # [B, S, 3E]
            if qkv_b is not None:
                qkv = qkv + qkv_b
            qkv = qkv.reshape(B, S, 3, nh, hd)
        else:
            three, nh, hd, _ = qkv_w.shape
            qkv = jnp.einsum("bse,thde->bsthd", h, qkv_w)
            if qkv_b is not None:
                qkv = qkv + qkv_b[None, None]
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        scale = 1.0 / math.sqrt(q.shape[-1])
        # shared attention core: additive mask + attention dropout + training
        ctx = _sdpa_core(q, k, v, mask, scale, False, attn_dropout_rate,
                         training).reshape(B, S, -1)
        out = ctx @ lin_w
        if lin_b is not None:
            out = out + lin_b
        if dropout_rate and training:
            keep = jax.random.bernoulli(_rng.next_key(), 1.0 - dropout_rate,
                                        out.shape)
            if mode == "upscale_in_train":
                out = jnp.where(keep, out / (1.0 - dropout_rate), 0.0).astype(out.dtype)
            else:
                out = jnp.where(keep, out, 0.0).astype(out.dtype)
        if add_residual:
            out = residual + out
        if not pre_layer_norm:
            mean = jnp.mean(out, axis=-1, keepdims=True)
            var = jnp.var(out, axis=-1, keepdims=True)
            out = (out - mean) * jax.lax.rsqrt(var + ln_epsilon)
            if post_s is not None:
                out = out * post_s
            if post_b is not None:
                out = out + post_b
        return out

    return apply_op(f, "fused_multi_head_attention", x, qkv_weight, qkv_bias,
                    linear_weight, linear_bias, pre_ln_scale, pre_ln_bias,
                    ln_scale, ln_bias, attn_mask)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu", ln1_epsilon=1e-5,
                      ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1, name=None):
    """Transformer FFN block in one traced op (reference incubate
    fused_feedforward): [pre-LN ->] linear1 -> act -> dropout1 -> linear2 ->
    dropout2 -> residual [-> post-LN]."""
    from ....framework import random as _rng

    def _drop(h, rate):
        if not rate or not training:
            return h
        keep = jax.random.bernoulli(_rng.next_key(), 1.0 - rate, h.shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, h / (1.0 - rate), 0.0).astype(h.dtype)
        return jnp.where(keep, h, 0.0).astype(h.dtype)

    def f(xv, w1, b1, w2, b2, s1, bb1, s2, bb2):
        residual = xv
        h = xv
        if pre_layer_norm:
            mean = jnp.mean(h, axis=-1, keepdims=True)
            var = jnp.var(h, axis=-1, keepdims=True)
            h = (h - mean) * jax.lax.rsqrt(var + ln1_epsilon)
            if s1 is not None:
                h = h * s1
            if bb1 is not None:
                h = h + bb1
        h = h @ w1
        if b1 is not None:
            h = h + b1
        act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
               "silu": jax.nn.silu}[activation]
        h = _drop(act(h), dropout1_rate)
        h = h @ w2
        if b2 is not None:
            h = h + b2
        out = residual + _drop(h, dropout2_rate)
        if not pre_layer_norm:
            mean = jnp.mean(out, axis=-1, keepdims=True)
            var = jnp.var(out, axis=-1, keepdims=True)
            out = (out - mean) * jax.lax.rsqrt(var + ln2_epsilon)
            if s2 is not None:
                out = out * s2
            if bb2 is not None:
                out = out + bb2
        return out

    return apply_op(f, "fused_feedforward", x, linear1_weight, linear1_bias,
                    linear2_weight, linear2_bias, ln1_scale, ln1_bias,
                    ln2_scale, ln2_bias)
