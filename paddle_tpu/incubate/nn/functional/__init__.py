"""incubate.nn.functional: fused functional ops (API parity; XLA does the fusing).
Reference: python/paddle/incubate/nn/functional/."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....ops import apply_op

__all__ = ["fused_linear", "fused_bias_act", "fused_rotary_position_embedding",
           "fused_rms_norm", "fused_layer_norm", "swiglu"]


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def f(v, w, b):
        if transpose_weight:
            w = w.T
        out = v @ w
        return out + b if b is not None else out

    return apply_op(f, "fused_linear", x, weight, bias)


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None, smooth=None,
                   act_method="gelu", compute_dtype="default", quant_scale=-1,
                   quant_round_type=0, quant_max_bound=0, quant_min_bound=0):
    def f(v, b):
        if b is not None:
            v = v + b
        if act_method == "gelu":
            return jax.nn.gelu(v)
        if act_method in ("swiglu",):
            a, g = jnp.split(v, 2, axis=-1)
            return jax.nn.silu(a) * g
        return getattr(jax.nn, act_method)(v)

    return apply_op(f, "fused_bias_act", x, bias)


def swiglu(x, y=None, name=None):
    if y is not None:
        return apply_op(lambda a, b: jax.nn.silu(a) * b, "swiglu", x, y)

    def f(v):
        a, b = jnp.split(v, 2, axis=-1)
        return jax.nn.silu(a) * b

    return apply_op(f, "swiglu", x)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """Reference: fused_rope (paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu).
    Layout [batch, seq, heads, head_dim]."""

    def rope_one(t, sin_v, cos_v):
        if t is None:
            return None
        if use_neox_rotary_style:
            half = t.shape[-1] // 2
            t1 = t[..., :half]
            t2 = t[..., half:]
            rot = jnp.concatenate([-t2, t1], axis=-1)
        else:
            t1 = t[..., 0::2]
            t2 = t[..., 1::2]
            rot = jnp.stack([-t2, t1], axis=-1).reshape(t.shape)
        return t * cos_v + rot * sin_v

    def f(qv, kv, vv, sin_v, cos_v, pos):
        S = qv.shape[1]
        D = qv.shape[-1]
        pos_applied = False
        if sin_v is None:
            inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
            if pos is not None:
                # absolute positions (KV-cache decode): build frequencies for
                # exactly these positions — a table of only S rows indexed by
                # absolute position would clip/misrotate past the first step.
                # A [B, S] pos builds PER-ROW frequencies (left-padded batches).
                pos_seq = pos.astype(jnp.float32)
                pos_applied = True
            else:
                pos_seq = jnp.arange(S, dtype=jnp.float32)
            freqs = pos_seq[..., None] * inv  # [S, D/2] or [B, S, D/2]
            if use_neox_rotary_style:
                emb = jnp.concatenate([freqs, freqs], axis=-1)
            else:
                emb = jnp.repeat(freqs, 2, axis=-1)
            if emb.ndim == 2:       # [S, D] → [1, S, 1, D]
                emb = emb[None, :, None, :]
            else:                   # [B, S, D] → [B, S, 1, D]
                emb = emb[:, :, None, :]
            sin_v = jnp.sin(emb)
            cos_v = jnp.cos(emb)
        else:
            if sin_v.ndim == 2:
                sin_v = sin_v[None, :, None, :]
                cos_v = cos_v[None, :, None, :]
            elif sin_v.ndim == 4 and sin_v.shape[2] != 1:
                pass
        if pos is not None and not pos_applied:
            sin_v = jnp.take(sin_v[0, :, 0], pos.astype(jnp.int32), axis=0)[:, :, None, :]
            cos_v = jnp.take(cos_v[0, :, 0], pos.astype(jnp.int32), axis=0)[:, :, None, :]
        sin_v = sin_v.astype(qv.dtype)
        cos_v = cos_v.astype(qv.dtype)
        outs = tuple(rope_one(t, sin_v, cos_v) for t in (qv, kv, vv) if t is not None)
        n_none = sum(t is None for t in (qv, kv, vv))
        full = []
        it = iter(outs)
        for t in (qv, kv, vv):
            full.append(None if t is None else next(it))
        return tuple(x for x in full if x is not None) if len(outs) > 1 else outs[0]

    out = apply_op(f, "fused_rope", q, k, v, sin, cos, position_ids)
    if isinstance(out, tuple):
        res = list(out)
        while len(res) < 3:
            res.append(None)
        return tuple(res[:3])
    return out, None, None


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1,
                   bias=None, residual=None, quant_scale=-1, quant_round_type=0,
                   quant_max_bound=0, quant_min_bound=0):
    def f(v, w, b, extra_bias, res):
        if extra_bias is not None:
            v = v + extra_bias
        if res is not None:
            v = v + res
        ms = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (v.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)).astype(v.dtype)
        out = out * w
        if b is not None:
            out = out + b
        return out

    return apply_op(f, "fused_rms_norm", x, norm_weight, norm_bias, bias, residual)


def fused_layer_norm(x, norm_weight, norm_bias=None, epsilon=1e-5, begin_norm_axis=-1,
                     bias=None, residual=None, **kw):
    def f(v, w, b, extra_bias, res):
        if extra_bias is not None:
            v = v + extra_bias
        if res is not None:
            v = v + res
        mean = jnp.mean(v, axis=-1, keepdims=True)
        var = jnp.var(v, axis=-1, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + epsilon)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return out

    return apply_op(f, "fused_layer_norm", x, norm_weight, norm_bias, bias, residual)
