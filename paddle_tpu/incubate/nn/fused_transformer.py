"""Fused transformer layers (API parity with incubate.nn.FusedMultiHeadAttention etc.).

Reference: python/paddle/incubate/nn/layer/fused_transformer.py:213 (FusedMultiHead
Attention), :534 (FusedFeedForward), :750 (FusedTransformerEncoderLayer). The CUDA
fused kernels become one traced region that XLA fuses; pre/post-LN + residual + dropout
orderings match the reference contract.
"""
from __future__ import annotations

from ...nn import functional as F
from ...nn.layer import Layer
from ...nn.layer_common import Dropout, Linear
from ...nn.layer_conv_norm import LayerNorm


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5, attn_dropout_rate=0.5,
                 kdim=None, vdim=None, normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, transpose_qkv_wb=False, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.qkv_proj = Linear(embed_dim, 3 * embed_dim, qkv_weight_attr, qkv_bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, linear_weight_attr, linear_bias_attr)
        self.pre_ln = LayerNorm(embed_dim, epsilon=epsilon)
        self.ln = LayerNorm(embed_dim, epsilon=epsilon)
        self.attn_dropout_rate = attn_dropout_rate
        self.dropout = Dropout(dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        residual = query
        x = self.pre_ln(query) if self.normalize_before else query
        qkv = self.qkv_proj(x)
        B, S = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape([B, S, 3, self.num_heads, self.head_dim])
        from ...ops.manipulation import unbind

        q, k, v = unbind(qkv, axis=2)
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                             dropout_p=self.attn_dropout_rate,
                                             training=self.training)
        out = out.reshape([B, S, self.embed_dim])
        out = self.out_proj(out)
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = Linear(d_model, dim_feedforward, linear1_weight_attr,
                              linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, linear2_weight_attr,
                              linear2_bias_attr)
        self.ln = LayerNorm(d_model, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)
        self.act_dropout = Dropout(act_dropout_rate if act_dropout_rate is not None
                                   else dropout_rate)
        self.activation = getattr(F, activation)

    def forward(self, src, cache=None):
        residual = src
        x = self.ln(src) if self.normalize_before else src
        x = self.linear2(self.act_dropout(self.activation(self.linear1(x))))
        out = residual + self.dropout(x)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate if attn_dropout_rate is not None
            else dropout_rate,
            normalize_before=normalize_before,
        )
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            act_dropout_rate=act_dropout_rate, activation=activation,
            normalize_before=normalize_before,
        )

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)
