"""paddle.incubate top-level API tail. Reference: python/paddle/incubate/
__init__.py __all__ — graph ops (thin aliases over paddle.geometric, the
reference keeps both spellings), fused softmax-mask ops, identity_loss, and
the LookAhead / ModelAverage optimizer wrappers."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..geometric import (
    reindex_graph as graph_reindex,
    sample_neighbors as graph_sample_neighbors,
    segment_max,
    segment_mean,
    segment_min,
    segment_sum,
    send_u_recv,
)
from ..ops import apply_op
from ..optimizer import Optimizer
from ..tensor import Tensor


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    """Reference: incubate/operators/graph_send_recv.py — the pre-geometric
    spelling of send_u_recv."""
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Reference: incubate/operators/graph_khop_sampler.py — multi-hop
    neighbor sampling: chain sample_neighbors per hop, then reindex."""
    from ..geometric import reindex_graph, sample_neighbors

    cur = input_nodes
    all_neighbors, all_counts = [], []
    for size in sample_sizes:
        neigh, cnt = sample_neighbors(row, colptr, cur, sample_size=size)
        all_neighbors.append(neigh)
        all_counts.append(cnt)
        cur = neigh
    import numpy as np

    neighbors = Tensor(jnp.asarray(np.concatenate(
        [np.asarray(n._value) for n in all_neighbors])))
    counts = Tensor(jnp.asarray(np.concatenate(
        [np.asarray(c._value) for c in all_counts])))
    # single flat reindex over the union (dst built per-hop by the caller in
    # the reference; the sampled edge list is what训练 consumes)
    src, dst, nodes = reindex_graph(input_nodes, neighbors, counts)
    if return_eids:
        raise NotImplementedError("sorted_eids return is not supported")
    return neighbors, counts, nodes, src


def identity_loss(x, reduction="none"):
    """Reference: incubate/operators/identity_loss.py — marks x as a loss for
    the IPU scheduler; numerically reduce-or-passthrough."""
    red = {0: "sum", 1: "mean", 2: "none"}.get(reduction, reduction)

    def f(v):
        if red == "sum":
            return jnp.sum(v)
        if red == "mean":
            return jnp.mean(v)
        return v

    return apply_op(f, "identity_loss", x)


def softmax_mask_fuse(x, mask, name=None):
    """Reference: incubate/operators/softmax_mask_fuse.py — softmax(x + mask)
    in one pass (XLA fuses; the CUDA kernel's raison d'etre)."""
    return apply_op(
        lambda v, m: jax.nn.softmax((v + m).astype(jnp.float32), axis=-1)
        .astype(v.dtype), "softmax_mask_fuse", x, mask)


def softmax_mask_fuse_upper_triangle(x):
    """Reference: softmax_mask_fuse_upper_triangle — causal-masked softmax
    without materializing the mask input."""

    def f(v):
        s = v.shape[-1]
        rows = jnp.arange(v.shape[-2])[:, None]
        cols = jnp.arange(s)[None, :]
        allowed = cols <= rows
        vv = jnp.where(allowed, v.astype(jnp.float32), jnp.float32(-1e9))
        return jax.nn.softmax(vv, axis=-1).astype(v.dtype)

    return apply_op(f, "softmax_mask_fuse_upper_triangle", x)


class LookAhead(Optimizer):
    """Reference: incubate/optimizer/lookahead.py — wraps an inner optimizer;
    every k steps the slow weights pull the fast weights back by alpha."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._slow = {}
        self._lk_step = 0

    def __getattr__(self, name):
        return getattr(self.__dict__["inner_optimizer"], name)

    def step(self):
        self.inner_optimizer.step()
        self._lk_step += 1
        if self._lk_step % self.k:
            return
        for _, p in self.inner_optimizer._parameters_list():
            slow = self._slow.get(id(p))
            if slow is None:
                slow = self._slow[id(p)] = p._value
                continue
            slow = slow + self.alpha * (p._value - slow)
            self._slow[id(p)] = slow
            p._value = slow

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def minimize(self, loss):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage(Optimizer):
    """Reference: incubate/optimizer/modelaverage.py — maintains the running
    average of parameters; apply()/restore() swap it in and out for eval."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000, name=None):
        super().__init__(0.0, parameters, None, None, name)
        self._sum = {}
        self._count = 0
        self._backup = None

    def step(self):
        self._count += 1
        for _, p in self._parameters_list():
            acc = self._sum.get(id(p))
            self._sum[id(p)] = p._value if acc is None else acc + p._value

    def apply(self, executor=None, need_restore=True):
        self._backup = {}
        for _, p in self._parameters_list():
            if id(p) in self._sum and self._count:
                self._backup[id(p)] = p._value
                p._value = (self._sum[id(p)] / self._count).astype(
                    p._value.dtype)

    def restore(self, executor=None):
        if self._backup is None:
            return
        for _, p in self._parameters_list():
            if id(p) in self._backup:
                p._value = self._backup[id(p)]
        self._backup = None
