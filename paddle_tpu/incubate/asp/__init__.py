"""ASP: automatic 2:4 structured sparsity.

Reference: python/paddle/incubate/asp/ (prune_model, decorate,
calculate_density, set/reset_excluded_layers; supported_layers_and_prune_func_map).
TPU-native note: the reference targets Ampere sparse tensor cores; on TPU the
mask brings model-compression semantics (and a future Pallas sparse-matmul
hook), so the API surface and the n:m mask math are kept bit-compatible while
execution stays dense-with-mask."""
from __future__ import annotations

import weakref

import numpy as np

import jax.numpy as jnp

from ...nn.layer import Layer
from ...tensor import Tensor

__all__ = ["calculate_density", "decorate", "prune_model",
           "set_excluded_layers", "reset_excluded_layers", "check_sparsity"]

_EXCLUDED: set = set()
# id(param) -> (weakref(param), mask): weakrefs let discarded models be
# garbage-collected and make id-reuse harmless (dead entries are dropped
# on the next decorated step)
_MASKS: dict = {}


def calculate_density(x) -> float:
    """Fraction of non-zeros. Reference: asp/utils.py calculate_density."""
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def _mask_1d(mat: np.ndarray, n: int, m: int) -> np.ndarray:
    """Keep the n largest-|x| of every m consecutive elements along the last
    dim (reference asp/utils.py get_mask_1d)."""
    flat = mat.reshape(-1, m)
    order = np.argsort(np.abs(flat), axis=1)
    mask = np.ones_like(flat, dtype=bool)
    np.put_along_axis(mask, order[:, : m - n], False, axis=1)
    return mask.reshape(mat.shape)


def check_sparsity(x, n=2, m=4) -> bool:
    """True iff every m-group along the last dim has <= n non-zeros.
    Reference: asp/utils.py check_sparsity (mask_1d check)."""
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    if arr.size % m:
        return False
    groups = arr.reshape(-1, m)
    return bool((np.count_nonzero(groups, axis=1) <= n).all())


def set_excluded_layers(param_names, main_program=None):
    """Reference: asp.set_excluded_layers — skip these params in prune/mask."""
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def _prunable_params(model: Layer):
    for name, p in model.named_parameters():
        if name in _EXCLUDED or p is None:
            continue
        # 2-D multiplicative weights only (reference prunes FC/conv kernels,
        # never biases or norms)
        if p.ndim >= 2 and name.endswith("weight") and p.shape[-1] % 4 == 0:
            yield name, p


def prune_model(model: Layer, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to every prunable weight in place; registers the masks
    so `decorate`d optimizers re-apply them after each step.

    Reference: asp.prune_model (asp/asp.py)."""
    if mask_algo != "mask_1d":
        raise NotImplementedError(
            f"mask_algo {mask_algo!r}: only 'mask_1d' is implemented (the "
            "reference's mask_2d_* search the 2-D pattern space; silently "
            "substituting mask_1d would diverge numerically)")
    masks = {}
    for name, p in _prunable_params(model):
        w = np.asarray(p._value)
        mask = _mask_1d(w.reshape(-1, w.shape[-1]), n, m).reshape(w.shape)
        jmask = jnp.asarray(mask, dtype=p._value.dtype)
        p._value = p._value * jmask
        if with_mask:
            _MASKS[id(p)] = (weakref.ref(p), jmask)
        masks[name] = mask
    return masks


class OptimizerWithSparsityGuarantee:
    """Re-applies registered masks after every optimizer step so pruned slots
    stay zero (reference asp/asp.py OptimizerWithSparsityGuarantee)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self):
        self._optimizer.step()
        dead = []
        for pid, (ref, mask) in _MASKS.items():
            p = ref()
            if p is None:
                dead.append(pid)
                continue
            p._value = p._value * mask
        for pid in dead:
            del _MASKS[pid]


def decorate(optimizer):
    """Reference: asp.decorate(optimizer)."""
    return OptimizerWithSparsityGuarantee(optimizer)
