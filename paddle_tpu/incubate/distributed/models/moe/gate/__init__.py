"""MoE gates. Reference parity: python/paddle/incubate/distributed/models/moe/gate/
(naive_gate.py, gshard_gate.py:31, switch_gate.py:31).

TPU-native: a gate is a Layer producing capacity-based routing tensors
(combine_weights [T,E,C], dispatch_mask [T,E,C], aux_loss) — the GShard dense
dispatch formulation, which keeps every shape static so the whole MoE block
compiles into one XLA program and the expert axis can shard over the 'ep' mesh
axis (a2a inserted by GSPMD).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ......nn import initializer as I
from ......nn.layer import Layer

__all__ = ["BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]


def topk_capacity_routing(probs, k: int, capacity: int, normalize_topk=True):
    """Dense top-k routing with per-expert capacity (pure jax; traced).

    probs: [T, E] softmax gate probabilities.
    Returns (combine [T,E,C] f32, dispatch [T,E,C] bool, top1_onehot [T,E]).
    Tokens beyond an expert's capacity are dropped (zero contribution), matching
    the reference's capacity semantics (gshard_gate.py / switch_gate.py).
    """
    T, E = probs.shape
    masked = probs
    sel = []  # (gate_val [T], onehot [T,E])
    for _ in range(k):
        idx = jnp.argmax(masked, axis=1)
        onehot = jax.nn.one_hot(idx, E, dtype=probs.dtype)
        gval = jnp.sum(probs * onehot, axis=1)
        sel.append((gval, onehot))
        masked = masked * (1.0 - onehot)
    if normalize_topk and k > 1:
        denom = sum(g for g, _ in sel) + 1e-9
        sel = [(g / denom, oh) for g, oh in sel]

    combine = jnp.zeros((T, E, capacity), probs.dtype)
    prev_counts = jnp.zeros((E,), probs.dtype)
    for gval, onehot in sel:
        # position of each token inside its chosen expert's buffer, counting
        # earlier-round assignments first (GShard ordering: all top-1 before top-2)
        loc_round = jnp.cumsum(onehot, axis=0) - onehot          # [T, E]
        loc = jnp.sum(loc_round * onehot, axis=1) + onehot @ prev_counts
        keep = (loc < capacity) & (jnp.sum(onehot, axis=1) > 0)
        loc_oh = jax.nn.one_hot(loc.astype(jnp.int32), capacity, dtype=probs.dtype)
        combine = combine + (
            (gval * keep)[:, None, None] * onehot[:, :, None] * loc_oh[:, None, :]
        )
        prev_counts = prev_counts + jnp.sum(onehot, axis=0)
    dispatch = combine > 0
    return combine, dispatch, sel[0][1]


def load_balance_loss(probs, top1_onehot):
    """GShard aux loss: E * sum_e mean_prob_e * mean_top1_frac_e (also the Switch
    formulation with N*sum(f_i*P_i))."""
    E = probs.shape[1]
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(top1_onehot, axis=0)
    return E * jnp.sum(me * ce)


class BaseGate(Layer):
    def __init__(self, num_expert, world_size=1):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = world_size * num_expert
        self.loss = None

    def get_loss(self, clear=True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss

    def set_loss(self, loss):
        self.loss = loss


class NaiveGate(BaseGate):
    """Reference naive_gate.py: linear scoring + top-k, no capacity drop
    (capacity = T so every selected token fits)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(num_expert, world_size)
        self.d_model = d_model
        self.top_k = topk
        self.weight = self.create_parameter(
            [d_model, self.tot_expert], default_initializer=I.XavierUniform()
        )

    def capacity_for(self, num_tokens):
        return int(num_tokens)

    def route(self, logits, capacity):
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        combine, dispatch, top1 = topk_capacity_routing(probs, self.top_k, capacity)
        return combine, dispatch, load_balance_loss(probs, top1)


class GShardGate(NaiveGate):
    """Reference gshard_gate.py:31 — top-2 with capacity + balance loss."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2, capacity=(1.2, 2.4),
                 random_routing=True, group=None):
        super().__init__(d_model, num_expert, world_size, topk=topk)
        self.capacity_factor = capacity[0] if isinstance(capacity, (tuple, list)) \
            else float(capacity)

    def capacity_for(self, num_tokens):
        import math

        return max(1, int(math.ceil(
            self.capacity_factor * self.top_k * num_tokens / self.tot_expert)))


class SwitchGate(NaiveGate):
    """Reference switch_gate.py:31 — top-1 with capacity + balance loss."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1, capacity=(1.2, 2.4),
                 switch_eps=0.1, group=None):
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.capacity_factor = capacity[0] if isinstance(capacity, (tuple, list)) \
            else float(capacity)
        self.switch_eps = switch_eps

    def capacity_for(self, num_tokens):
        import math

        return max(1, int(math.ceil(
            self.capacity_factor * num_tokens / self.tot_expert)))
