"""MoE gates. Reference parity: python/paddle/incubate/distributed/models/moe/gate/
(naive_gate.py, gshard_gate.py:31, switch_gate.py:31).

TPU-native: a gate is a Layer producing capacity-based routing tensors
(combine_weights [T,E,C], dispatch_mask [T,E,C], aux_loss) — the GShard dense
dispatch formulation, which keeps every shape static so the whole MoE block
compiles into one XLA program and the expert axis can shard over the 'ep' mesh
axis (a2a inserted by GSPMD).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ......nn import initializer as I
from ......nn.layer import Layer

__all__ = ["BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]


def topk_capacity_routing(probs, k: int, capacity: int, normalize_topk=True):
    """Dense top-k routing with per-expert capacity (pure jax; traced).

    probs: [T, E] softmax gate probabilities.
    Returns (combine [T,E,C] f32, dispatch [T,E,C] bool, top1_onehot [T,E]).
    Tokens beyond an expert's capacity are dropped (zero contribution), matching
    the reference's capacity semantics (gshard_gate.py / switch_gate.py).

    Derived from the SAME routing decisions as the index form (one
    implementation — dense-vs-index parity holds by construction): the dense
    tensors are a scatter of the flat (eid, loc, keep, gval) indices."""
    T, E = probs.shape
    eids, locs, keeps, gvals, top1 = topk_capacity_routing_indices(
        probs, k, capacity, normalize_topk)
    combine = jnp.zeros((T, E, capacity), probs.dtype)
    t_idx = jnp.broadcast_to(jnp.arange(T), eids.shape)
    # dropped assignments scatter out of bounds -> mode="drop" discards them
    e_safe = jnp.where(keeps, eids, E)
    combine = combine.at[t_idx.reshape(-1), e_safe.reshape(-1),
                         locs.reshape(-1)].add(
        (gvals * keeps).reshape(-1).astype(probs.dtype), mode="drop")
    dispatch = combine > 0
    return combine, dispatch, top1


def topk_capacity_routing_indices(probs, k: int, capacity: int,
                                  normalize_topk=True):
    """Same routing DECISIONS as topk_capacity_routing, returned as flat
    indices instead of [T,E,C] one-hot tensors: (eids, locs, keeps, gvals)
    each [k, T], plus the top-1 one-hot for the balance loss. The index form
    feeds gather/scatter dispatch — O(k*T*d) instead of the dense einsum's
    O(T*E*C*d), the MoE-dispatch analog of the reference's fused_moe_kernel
    (fusion/cutlass/fused_moe_kernel.cu) grouped-GEMM shape."""
    T, E = probs.shape
    masked = probs
    prev_counts = jnp.zeros((E,), probs.dtype)
    eids, locs, keeps, gvals = [], [], [], []
    top1 = None
    for r in range(k):
        idx = jnp.argmax(masked, axis=1)
        onehot = jax.nn.one_hot(idx, E, dtype=probs.dtype)
        if r == 0:
            top1 = onehot
        gval = jnp.sum(probs * onehot, axis=1)
        loc_round = jnp.cumsum(onehot, axis=0) - onehot
        loc = jnp.sum(loc_round * onehot, axis=1) + onehot @ prev_counts
        keep = loc < capacity
        eids.append(idx.astype(jnp.int32))
        locs.append(loc.astype(jnp.int32))
        keeps.append(keep)
        gvals.append(gval)
        prev_counts = prev_counts + jnp.sum(onehot, axis=0)
        masked = masked * (1.0 - onehot)
    gvals = jnp.stack(gvals)
    if normalize_topk and k > 1:
        gvals = gvals / (jnp.sum(gvals, axis=0, keepdims=True) + 1e-9)
    return (jnp.stack(eids), jnp.stack(locs), jnp.stack(keeps), gvals, top1)


def load_balance_loss(probs, top1_onehot):
    """GShard aux loss: E * sum_e mean_prob_e * mean_top1_frac_e (also the Switch
    formulation with N*sum(f_i*P_i))."""
    E = probs.shape[1]
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(top1_onehot, axis=0)
    return E * jnp.sum(me * ce)


class BaseGate(Layer):
    def __init__(self, num_expert, world_size=1):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = world_size * num_expert
        self.loss = None

    def get_loss(self, clear=True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss

    def set_loss(self, loss):
        self.loss = loss


class NaiveGate(BaseGate):
    """Reference naive_gate.py: linear scoring + top-k, no capacity drop
    (capacity = T so every selected token fits)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(num_expert, world_size)
        self.d_model = d_model
        self.top_k = topk
        self.weight = self.create_parameter(
            [d_model, self.tot_expert], default_initializer=I.XavierUniform()
        )

    def capacity_for(self, num_tokens):
        return int(num_tokens)

    def route(self, logits, capacity):
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        combine, dispatch, top1 = topk_capacity_routing(probs, self.top_k, capacity)
        return combine, dispatch, load_balance_loss(probs, top1)

    def route_indices(self, logits, capacity):
        """(eids, locs, keeps, gvals) [k,T] + aux loss — the gather/scatter
        dispatch form (see topk_capacity_routing_indices)."""
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        eids, locs, keeps, gvals, top1 = topk_capacity_routing_indices(
            probs, self.top_k, capacity)
        return eids, locs, keeps, gvals, load_balance_loss(probs, top1)


class GShardGate(NaiveGate):
    """Reference gshard_gate.py:31 — top-2 with capacity + balance loss."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2, capacity=(1.2, 2.4),
                 random_routing=True, group=None):
        super().__init__(d_model, num_expert, world_size, topk=topk)
        self.capacity_factor = capacity[0] if isinstance(capacity, (tuple, list)) \
            else float(capacity)

    def capacity_for(self, num_tokens):
        import math

        return max(1, int(math.ceil(
            self.capacity_factor * self.top_k * num_tokens / self.tot_expert)))


class SwitchGate(NaiveGate):
    """Reference switch_gate.py:31 — top-1 with capacity + balance loss."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1, capacity=(1.2, 2.4),
                 switch_eps=0.1, group=None):
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.capacity_factor = capacity[0] if isinstance(capacity, (tuple, list)) \
            else float(capacity)
        self.switch_eps = switch_eps

    def capacity_for(self, num_tokens):
        import math

        return max(1, int(math.ceil(
            self.capacity_factor * num_tokens / self.tot_expert)))
