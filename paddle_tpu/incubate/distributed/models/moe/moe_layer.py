"""MoELayer. Reference parity: python/paddle/incubate/distributed/models/moe/
moe_layer.py:261 (MoELayer: gate -> dispatch -> experts -> combine).

TPU-native: dense GShard dispatch (einsum over one-hot routing tensors) instead
of the reference's global_scatter/global_gather variable-count a2a — every shape
is static, the whole block compiles into one XLA program, and expert parallelism
comes from sharding the expert-major tensors over the 'ep'/'moe' mesh axis
(GSPMD emits the all_to_all). Uniform experts run under jax.vmap over stacked
parameters (one batched matmul on the MXU per projection, all experts at once).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....nn.layer import Layer
from .....nn.layer_common import LayerList
from .....ops import apply_op
from .....tensor import Tensor
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

__all__ = ["MoELayer"]


def _ep_axis():
    from .....distributed.mesh import get_mesh

    mesh = get_mesh()
    if mesh is None:
        return None, None
    for name in ("ep", "moe"):
        if name in mesh.dim_names and mesh.get_dim_size(name) > 1:
            return mesh, name
    return None, None


class MoELayer(Layer):
    """Mixture of experts.

    Args mirror the reference: `d_model`, `experts` (list/LayerList of expert
    Layers — uniform experts get the stacked-vmap fast path), `gate` (a BaseGate,
    or dict/str naming 'gshard' | 'switch' | 'naive'), `moe_group` unused on TPU
    (the mesh 'ep' axis plays that role), `recompute_interval` wraps expert
    compute in jax.checkpoint when nonzero.

    After forward, `self.l_aux` holds the load-balance loss (also pushed to
    gate.set_loss, matching reference usage `layer.gate.get_loss()`).
    """

    def __init__(self, d_model, experts, gate=None, moe_group=None, mp_group=None,
                 recompute_interval=0, dispatch_mode=None, **kwargs):
        super().__init__()
        if dispatch_mode not in (None, "index", "dense"):
            raise ValueError(f"dispatch_mode must be None, 'index' or 'dense', "
                             f"got {dispatch_mode!r}")
        # 'index': gather/scatter dispatch+combine, O(k*T*d) — the grouped-GEMM
        # shape (reference fused_moe_kernel.cu role); 'dense': the one-hot
        # einsum formulation, O(T*E*C*d), kept as the parity oracle. None
        # (default): 'index' when the gate provides route_indices, else
        # 'dense'; an EXPLICIT 'index' with an incapable gate raises rather
        # than silently running the quadratic path.
        self.d_model = d_model
        self.experts = experts if isinstance(experts, LayerList) else LayerList(experts)
        num_expert = len(self.experts)
        if gate is None:
            gate = "gshard"
        if isinstance(gate, dict):
            gate = gate.get("type", "gshard")
        if isinstance(gate, str):
            gate = {"gshard": GShardGate, "switch": SwitchGate, "naive": NaiveGate}[
                gate](d_model, num_expert)
        if not isinstance(gate, BaseGate):
            raise TypeError(f"gate must be a BaseGate, got {type(gate)}")
        self.gate = gate
        gate_has_indices = hasattr(gate, "route_indices")
        if dispatch_mode == "index" and not gate_has_indices:
            raise ValueError(
                "dispatch_mode='index' requires the gate to implement "
                f"route_indices; {type(gate).__name__} does not — pass "
                "dispatch_mode='dense' or None (auto)")
        self.dispatch_mode = (dispatch_mode
                              or ("index" if gate_has_indices else "dense"))
        self.recompute_interval = recompute_interval
        self.l_aux = None
        self._uniform = self._check_uniform()

    def _check_uniform(self):
        if not len(self.experts):
            return False
        sd0 = self.experts[0].state_dict()
        shapes = {k: tuple(t.shape) for k, t in sd0.items()}
        for e in self.experts:
            sd = e.state_dict()
            if {k: tuple(t.shape) for k, t in sd.items()} != shapes:
                return False
        return True

    def forward(self, x):
        orig_shape = list(x.shape)
        d = orig_shape[-1]
        T = 1
        for s in orig_shape[:-1]:
            T *= s
        E = len(self.experts)
        capacity = min(self.gate.capacity_for(T), T)
        k = getattr(self.gate, "top_k", 2)
        names = list(self.experts[0].state_dict().keys())
        expert_params = [e.state_dict()[n] for e in self.experts for n in names]
        uniform = self._uniform
        experts = self.experts
        gate = self.gate
        recompute = self.recompute_interval > 0

        index_mode = self.dispatch_mode == "index"

        def f(xv, gw, *pvals):
            xf = xv.reshape(T, d)
            logits = xf @ gw.astype(xf.dtype)
            if index_mode:
                eids, locs, keeps, gvals, l_aux = gate.route_indices(
                    logits, capacity)
                # slot address per (round, token); dropped tokens target the
                # sentinel slot E*C which backs a zero row
                slot = jnp.where(keeps, eids * capacity + locs, E * capacity)
                token_for = jnp.full((E * capacity + 1,), T, jnp.int32)
                t_idx = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32),
                                         slot.shape)
                token_for = token_for.at[slot.reshape(-1)].set(
                    t_idx.reshape(-1), mode="drop")
                x_pad = jnp.concatenate([xf, jnp.zeros((1, d), xf.dtype)])
                disp = x_pad[token_for[:-1]].reshape(E, capacity, d)
                combine = None
            else:
                combine, dispatch, l_aux = gate.route(logits, capacity)
                combine = combine.astype(xf.dtype)
                disp = jnp.einsum("tec,td->ecd", dispatch.astype(xf.dtype), xf)
            mesh, ax = _ep_axis()
            if mesh is not None and isinstance(disp, jax.core.Tracer) and E % \
                    mesh.get_dim_size(ax) == 0:
                from jax.sharding import NamedSharding, PartitionSpec

                disp = jax.lax.with_sharding_constraint(
                    disp, NamedSharding(mesh.jax_mesh, PartitionSpec(ax)))
            P = len(names)
            if uniform:
                stacked = {
                    n: jnp.stack([pvals[e * P + i] for e in range(E)])
                    for i, n in enumerate(names)
                }

                def apply_one(params, xe):
                    out = experts[0].functional_call(params, Tensor(xe))
                    return out._value if isinstance(out, Tensor) else out

                if recompute:
                    apply_one = jax.checkpoint(apply_one)
                eo = jax.vmap(apply_one)(stacked, disp)
            else:
                outs = []
                for e in range(E):
                    params = {n: pvals[e * P + i] for i, n in enumerate(names)}
                    out = experts[e].functional_call(params, Tensor(disp[e]))
                    outs.append(out._value if isinstance(out, Tensor) else out)
                eo = jnp.stack(outs)
            if mesh is not None and isinstance(eo, jax.core.Tracer) and E % \
                    mesh.get_dim_size(ax) == 0:
                from jax.sharding import NamedSharding, PartitionSpec

                eo = jax.lax.with_sharding_constraint(
                    eo, NamedSharding(mesh.jax_mesh, PartitionSpec(ax)))
            if index_mode:
                eo_pad = jnp.concatenate(
                    [eo.reshape(E * capacity, d).astype(jnp.float32),
                     jnp.zeros((1, d), jnp.float32)])
                w = (gvals * keeps).astype(jnp.float32)        # [k, T]
                y = jnp.sum(w[..., None] * eo_pad[slot], axis=0)
                y = y.astype(xf.dtype)
            else:
                y = jnp.einsum("ecd,tec->td", eo.astype(jnp.float32),
                               combine.astype(jnp.float32)).astype(xf.dtype)
            return y.reshape(orig_shape), l_aux

        y, l_aux = apply_op(f, "moe_layer", x, self.gate.weight, *expert_params,
                            nout=2)
        l_aux = l_aux if isinstance(l_aux, Tensor) else Tensor(l_aux)
        self.l_aux = l_aux
        self.gate.set_loss(l_aux)
        return y
