"""Sharding & collective lint — static SPMD layout contracts (ISSUE-20).

Since ISSUE-12 tensor-parallel-sharded the serving step programs, the repo
has DECLARED a layout (``distributed/mesh.py SpecLayout``) but nothing
verified that the compiled artifacts honor it: GSPMD is free to insert
resharding collectives wherever the declared layout and the program's real
dataflow disagree, and every such insertion is latency paid on every launch
of a program that runs thousands of times per second. This module is the
fifth lint leg (graph / thread / compile-surface / HBM / **comms**): a
static pass over the POST-SPMD compiled HLO of the serving step programs.

Why compiled HLO and not the lowered StableHLO: GSPMD partitions at
*compile* time. The pre-partitioning StableHLO of the tp=2 decode tick
carries only ``@Sharding`` custom-call annotations — zero collectives —
while the compiled module carries every all-reduce/all-gather/
collective-permute XLA actually inserted. The lowered module cannot answer
"what crosses the interconnect"; the compiled one is the ground truth the
deploy review needs, and jax hands it over for free
(``run.lower(*args).compile().as_text()`` + ``input_shardings``).

Two halves, five rules:

* **Collective inventory** — every ``all-gather`` / ``all-reduce`` /
  ``reduce-scatter`` / ``all-to-all`` / ``collective-permute`` in the
  compiled module, with shape, dtype, replica-group size and estimated
  bytes-on-wire (per participating chip, ring formulas — see docs/PERF.md).
  Ops inside the decode scan (``/while/`` in their op_name metadata) count
  once per scanned step. Rules: ``implicit-reshard`` (HIGH — a collective
  kind no declared SpecLayout transition explains), ``comms-over-budget``
  (HIGH — per-tick wire bytes vs the per-chip ICI table in
  ``observability/xla.py``, the bandwidth sibling of ``device_peak_flops``).
* **Layout contract** — the compiled program's actual ``input_shardings`` /
  ``output_shardings`` against the declared ``SpecLayout.step_contract()``.
  Rules: ``layout-contract-drift`` (HIGH — a contract glob matches an
  argument whose compiled sharding disagrees, or matches nothing at all),
  ``replicated-large-buffer`` (WARN, strict-HIGH — a >=1 MiB input
  replicated over tp that a SpecLayout axis could shard; the LoRA adapter
  bank is the known candidate), ``dead-mesh-axis`` (WARN — a declared mesh
  axis nothing in the program set uses; ``dp`` trips it by design and is
  builtin-allowlisted with its reason).

What the first self-check caught (the linter's reason to exist, written up
in docs/ANALYSIS.md): the fused qkv projection's column shard does NOT land
on head boundaries — at tp=2 the 192-wide qkv splits at 96, straddling the
k and v head groups, so XLA patches the split with per-layer
collective-permutes (models/gpt.py ``split_qkv``); the fused swiglu
gate/up halves straddle the same way; and top-k sampling over the
vocab-sharded logits lowers to a distributed sort with all-to-alls. All
three are real cross-chip traffic nobody declared — carried in
``BUILTIN_COMMS_ALLOWLIST`` with reasons until the layouts are interleaved,
exactly the "clean or explained" bar the other lint legs hold.

Gating: the ``comms_surface`` zoo entry (``--self-check``), the CLI
``--comms [NAME|PATH]`` (per-program collective table, the deploy-review
artifact; PATH = strict fixture mode over tests/comms_fixtures/), the bench
``comms_lint`` leg, and the MULTICHIP dryrun's fleet phase. PR 5's narrower
``collective-axis`` rule stays: it checks axis *names* inside the traced
jaxpr; this pass checks the *compiled* artifact — different failure modes.
"""
from __future__ import annotations

import dataclasses
import fnmatch
import json
import os
import re

from .core import Report, fmt_bytes
from .findings import HIGH, WARN, Allowlist, AllowlistEntry, Finding

__all__ = [
    "COMMS_RULES", "BUILTIN_COMMS_ALLOWLIST", "CollectiveOp",
    "CommsEstimate", "CommsBudget", "collective_inventory", "bytes_on_wire",
    "compiled_comms_surface", "step_comms_surfaces", "render_comms_table",
    "analyze_comms_surfaces", "analyze_step_comms",
    "sampled_logits_gather_surface", "comms_fixture_reports",
    "DEFAULT_TPOT_BUDGET_S", "REPLICATED_BUFFER_MIN_BYTES",
]

COMMS_RULES = {
    "implicit-reshard":
        "a collective in the compiled module that no declared SpecLayout "
        "transition explains — GSPMD is resharding mid-program behind the "
        "layout contract's back, paid on every launch",
    "layout-contract-drift":
        "a compiled input/output sharding disagrees with the declared "
        "SpecLayout contract entry that names it (or a contract glob "
        "matches nothing — the contract rotted off the program)",
    "comms-over-budget":
        "per-tick collective bytes-on-wire cannot cross the per-chip ICI "
        "inside the tick wall budget at the configured tp (silent when the "
        "interconnect is unknown, e.g. CPU)",
    "replicated-large-buffer":
        "a >=1 MiB program input is fully replicated over tp though a mesh "
        "axis could shard one of its dimensions (HIGH in strict mode; the "
        "LoRA adapter bank is the known candidate)",
    "dead-mesh-axis":
        "a declared mesh axis that no input/output sharding in the program "
        "set uses — topology bought, never wired",
}

# tick wall budget: decode_steps tokens per tick, each owed the default
# p99 TPOT objective shipped in observability/slo.py (tpot_p99_ms: 50)
DEFAULT_TPOT_BUDGET_S = 0.050
REPLICATED_BUFFER_MIN_BYTES = 1 << 20

_STEP_PATHS = ("prefill_chunk", "decode_step", "verify_step")

# ============================================================== HLO parsing
# Post-SPMD HLO types print as e.g. ``f32[2,1,64]{2,1,0}`` (per-device
# shapes) — NOT the ``tensor<...>`` syntax rules.py parses out of StableHLO,
# hence a second tiny parser instead of reusing _tensor_bytes.
_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}
_HLO_TYPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_COLLECTIVE_RE = re.compile(
    r"=\s+(?P<result>\([^)]*\)|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(?P<kind>all-gather|all-reduce|reduce-scatter|all-to-all|"
    r"collective-permute)(?:-start)?\(")
_IOTA_GROUPS_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=")
_LIST_GROUPS_RE = re.compile(r"replica_groups=\{\{([0-9,]+)\}")
_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[0-9,]+\}(?:,\{[0-9,]+\})*)\}")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_SOURCE_RE = re.compile(r'source_file="([^"]*)"\s+source_line=(\d+)')


def _hlo_result_bytes(result: str):
    """(dtype, bytes) of a printed HLO result type — tuple types sum their
    elements and report the first element's dtype."""
    total, dtype = 0, ""
    for dt, dims in _HLO_TYPE_RE.findall(result):
        if dt not in _HLO_DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _HLO_DTYPE_BYTES[dt]
        dtype = dtype or dt
    return dtype, total


def bytes_on_wire(kind, buffer_bytes, group_size) -> int:
    """Bytes one participating chip puts on the ICI per execution of one
    collective, ring algorithms (the formulas docs/PERF.md derives):

    * all-gather (printed result = the full gathered buffer G):  G(n-1)/n
    * all-reduce (printed result = the full buffer B):          2B(n-1)/n
    * reduce-scatter (printed result = the scattered shard Bs): Bs(n-1)
    * all-to-all (printed result = the per-chip buffer B):       B(n-1)/n
    * collective-permute:                                        B
    """
    n = max(1, int(group_size))
    b = int(buffer_bytes)
    if kind == "all-reduce":
        return 2 * b * (n - 1) // n
    if kind == "reduce-scatter":
        return b * (n - 1)
    if kind == "collective-permute":
        return b
    return b * (n - 1) // n            # all-gather / all-to-all


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One collective in a compiled module: what crosses chips, how big,
    how often per launch, and which source line put it there."""
    kind: str
    result: str                  # printed (per-device) result type
    dtype: str
    buffer_bytes: int
    group_size: int
    count: int                   # executions per program launch
    wire_bytes: int              # bytes-on-wire per launch (count folded in)
    op_name: str = ""
    where: str = ""

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _short_where(source_file, source_line, op_name):
    path = source_file
    for anchor in ("paddle_tpu/", "site-packages/"):
        i = path.rfind(anchor)
        if i >= 0:
            path = path[i:]
            break
    tail = ""
    if op_name:
        tail = f" ({op_name.rsplit('/', 1)[-1]})"
    return f"{path}:{source_line}{tail}" if path else op_name


def collective_inventory(hlo_text, *, loop_steps=1):
    """Parse every collective out of post-SPMD compiled HLO text.

    ``loop_steps`` is the launch multiplier for ops that live inside the
    program's while loop (the decode scan): XLA prints the loop body once
    but the op runs once per scanned step. Async ``-start``/``-done``
    pairs count once (the ``-start`` carries the transfer)."""
    ops = []
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if m is None:
            continue
        kind, result = m.group("kind"), m.group("result")
        dtype, nbytes = _hlo_result_bytes(result)
        group = 1
        gm = _IOTA_GROUPS_RE.search(line)
        if gm:
            group = int(gm.group(2))
        else:
            gm = _LIST_GROUPS_RE.search(line)
            if gm:
                group = len(gm.group(1).split(","))
            elif kind == "collective-permute":
                pm = _PAIRS_RE.search(line)
                if pm:
                    group = pm.group(1).count("{")
        op_name = (_OP_NAME_RE.search(line) or [None, ""])[1]
        sm = _SOURCE_RE.search(line)
        where = _short_where(sm.group(1), sm.group(2), op_name) if sm \
            else op_name
        count = int(loop_steps) if "/while/" in op_name else 1
        ops.append(CollectiveOp(
            kind=kind, result=result.split("{")[0], dtype=dtype,
            buffer_bytes=nbytes, group_size=group, count=count,
            wire_bytes=bytes_on_wire(kind, nbytes, group) * count,
            op_name=op_name, where=where))
    return ops


# ======================================================== sharding flatten
def _normalize_spec(entries) -> tuple:
    """A PartitionSpec-ish sequence as a canonical tuple: sub-tuples kept,
    trailing Nones dropped (jax prints P('tp') and P('tp', None) for the
    same placement)."""
    out = [tuple(e) if isinstance(e, (list, tuple)) else e
           for e in (entries or ())]
    while out and out[-1] is None:
        out.pop()
    return tuple(out)


def _spec_of(sharding) -> tuple:
    spec = getattr(sharding, "spec", None)
    return _normalize_spec(tuple(spec)) if spec is not None else ()


def _flat_labeled(labels, tree):
    """Flatten one level of top-level args against their labels, then each
    subtree by path — ``state.blocks.0.attn.qkv_proj.weight``,
    ``k_pages.1`` — dot-joined so contract globs never need fnmatch's
    bracket syntax."""
    import jax.tree_util as jtu

    out = []
    for label, sub in zip(labels, tree):
        for path, leaf in jtu.tree_flatten_with_path(sub)[0]:
            key = label
            for p in path:
                part = getattr(p, "key", getattr(p, "idx", None))
                key += f".{part}" if part is not None else ""
            out.append((key, leaf))
    return out


def compiled_comms_surface(compiled, *, name, labels=None, args=None,
                           mesh_axes=None, loop_steps=1) -> dict:
    """The comms view of one jax ``Compiled``: collective inventory +
    flattened input/output sharding specs + input sizes. Works on any
    compiled program (zoo step programs, fixtures, the sampled-logits
    probe) — everything downstream is pure data."""
    import jax.tree_util as jtu

    ops = collective_inventory(compiled.as_text(), loop_steps=loop_steps)
    in_specs, in_bytes = {}, {}
    try:
        ins, _kwargs = compiled.input_shardings
    except Exception:
        ins = None
    if ins is not None:
        if labels is None:
            labels = tuple(f"arg{i}" for i in range(len(ins)))
        for key, sh in _flat_labeled(labels, ins):
            in_specs[key] = _spec_of(sh)
        if args is not None:
            import numpy as np

            for key, leaf in _flat_labeled(labels, args):
                try:     # PRNG key arrays have no byte width — count as 0
                    nbytes = int(np.prod(leaf.shape)
                                 * np.dtype(leaf.dtype).itemsize)
                except Exception:
                    nbytes = 0
                in_bytes[key] = {"bytes": nbytes,
                                 "shape": tuple(getattr(leaf, "shape", ()))}
    out_specs = {}
    try:
        outs = compiled.output_shardings
        for path, sh in jtu.tree_flatten_with_path(outs)[0]:
            key = "out" + "".join(
                f".{getattr(p, 'key', getattr(p, 'idx', ''))}" for p in path)
            out_specs[key] = _spec_of(sh)
    except Exception:
        pass
    return {
        "name": name,
        "mesh_axes": dict(mesh_axes or {}),
        "loop_steps": int(loop_steps),
        "ops": ops,
        "bytes_per_launch": sum(op.wire_bytes for op in ops),
        "input_specs": in_specs,
        "input_bytes": in_bytes,
        "output_specs": out_specs,
    }


# ============================================================ the step zoo
def _build_step_program(path):
    """Build one continuous-scheduler step program at the zoo smoke
    geometry under the CURRENT mesh and return (model, args, name,
    loop_steps, slots, width) — the same construction the zoo report
    functions use, minus the jaxpr analysis."""
    import jax
    import numpy as np

    from .zoo import _continuous_smoke

    model, kv, tbl, ids, S, C, NEW, T, jnp = _continuous_smoke()
    pools = (tuple(kv.k_pages), tuple(kv.v_pages))
    temps = jnp.zeros((S,), jnp.float32)
    top_ks = jnp.zeros((S,), jnp.int32)
    state = model._decode_state(jnp.bfloat16)
    key = jax.random.key(0)
    i32 = lambda a: jnp.asarray(a, jnp.int32)  # noqa: E731
    if path == "prefill_chunk":
        offs = np.zeros(S, np.int64)
        lens = np.asarray([C, 0], np.int64)
        model.prefill_chunk(ids, offs, lens, kv, tbl)
        args = (state, jnp.asarray(ids), i32(offs), i32(lens), i32(tbl),
                temps, top_ks, *pools, key)
        return model, args, "gpt.decode.paged_prefill_chunk_tp", 1, S, C
    model.prefill_chunk(ids, np.zeros(S, np.int64),
                        np.asarray([C, 0], np.int64), kv, tbl)
    act = np.asarray([True, False])
    lmax = np.asarray([C + NEW, 0], np.int64)
    if path == "decode_step":
        tok = np.zeros(S, np.int64)
        lens = np.asarray([C, 0], np.int64)
        model.decode_step(tok, lens, act, kv, tbl, steps=T, max_lens=lmax)
        args = (state, jnp.asarray(tok), i32(lens), jnp.asarray(act),
                i32(lmax), i32(tbl), temps, top_ks, *pools, key)
        # the scan body's collectives run once per scanned token
        return model, args, "gpt.decode.paged_step_tp", T, S, T
    if path == "verify_step":
        K = 3
        chunk = np.zeros((S, K + 1), np.int64)
        chunk[0] = np.random.RandomState(1).randint(0, 512, K + 1)
        offs = np.asarray([C, 0], np.int64)
        dlens = np.asarray([K, 0], np.int64)
        model.verify_step(chunk, offs, dlens, act, kv, tbl, max_lens=lmax)
        args = (state, jnp.asarray(chunk), i32(offs), i32(dlens),
                jnp.asarray(act), i32(lmax), i32(tbl), temps, top_ks,
                *pools, key)
        return model, args, "gpt.decode.paged_verify_step_tp", 1, S, K + 1
    raise ValueError(f"no comms surface for step path {path!r}")


def step_comms_surfaces(paths=None):
    """Compile the serving step programs under the ("dp","tp") serving mesh
    and return their comms surfaces. tp=2 when the process has the devices
    (tier-1 forces 8 host devices; a TPU slice always qualifies), else the
    degenerate tp=1 surface — no collectives, nothing sharded — so the
    pass still runs everywhere."""
    import jax

    from ..distributed.mesh import get_mesh, serving_mesh, set_mesh
    from ..models.generation import step_arg_labels

    prev = get_mesh()
    tp = 2 if len(jax.devices()) >= 2 else 1
    serving_mesh(dp=1, tp=tp)
    try:
        surfaces = []
        for path in paths or _STEP_PATHS:
            model, args, name, loop, slots, width = _build_step_program(path)
            compiled = model.compiled_step_program(path, slots, width, args)
            s = compiled_comms_surface(
                compiled, name=name, labels=step_arg_labels(path),
                args=args, mesh_axes={"dp": 1, "tp": tp}, loop_steps=loop)
            s["path"] = path
            s["tp"] = tp
            surfaces.append(s)
        return surfaces
    finally:
        set_mesh(prev)


# declared OUTPUT layout per step path: the KV pool layers stay
# head-sharded on the way out (same SpecLayout.kv_pool placement the
# inputs declare); sampled tokens come back replicated to the host.
_OUTPUT_CONTRACT = {
    "prefill_chunk": {"out.0": (), "out.1.*": ("tp",), "out.2.*": ("tp",)},
    "decode_step": {"out.0": (), "out.1.*": ("tp",), "out.2.*": ("tp",)},
    "verify_step": {"out.0": (), "out.1": (),
                    "out.2.*": ("tp",), "out.3.*": ("tp",)},
}


def render_comms_table(surfaces) -> str:
    """The deploy-review artifact ``--comms`` prints: one row per
    collective with its wire cost, per program."""
    lines = []
    for s in surfaces:
        tp = s.get("tp") or s.get("mesh_axes", {}).get("tp", "?")
        lines.append(f"== comms surface: {s['name']} (tp={tp}) ==")
        if not s["ops"]:
            lines.append("  no collectives")
        for op in s["ops"]:
            lines.append(
                f"  {op.kind:18s} {op.result:22s} group={op.group_size} "
                f"x{op.count:<3d} {fmt_bytes(op.wire_bytes):>10s} on wire"
                f"  @ {op.where}")
        lines.append(f"  per-launch total {fmt_bytes(s['bytes_per_launch'])}"
                     " on wire per chip")
    return "\n".join(lines)


# ================================================================ the rules
def _rule_implicit_reshard(surface, expected):
    """HIGH: a collective kind no declared layout transition explains."""
    for op in surface["ops"]:
        if op.kind in expected:
            continue
        yield Finding(
            "implicit-reshard", HIGH,
            f"{op.kind} {op.result} (group={op.group_size}, x{op.count} "
            f"per launch, {fmt_bytes(op.wire_bytes)} on wire) has no "
            f"declared layout transition — declared transitions: "
            f"{sorted(expected)}",
            where=op.where, subject=surface["name"],
            remediation="align the sharded axis with the producing layout "
                        "(interleave per-shard head groups for fused "
                        "projections), declare the transition in "
                        "SpecLayout.expected_collectives, or allowlist it "
                        "with the reason")


def _rule_layout_contract(surface, contract):
    """HIGH: compiled sharding disagrees with the declared contract."""
    actual = {}
    actual.update(surface.get("input_specs", {}))
    actual.update(surface.get("output_specs", {}))
    if not contract or not actual:
        return
    for glob, want in sorted(contract.items()):
        want_n = _normalize_spec(want)
        hits = [k for k in actual if fnmatch.fnmatch(k, glob)]
        if not hits:
            yield Finding(
                "layout-contract-drift", HIGH,
                f"contract entry {glob!r} -> {want_n} matches no input or "
                "output of the compiled program — the contract rotted off "
                "the argument names",
                subject=surface["name"],
                remediation="re-aim the contract glob at the current "
                            "argument labels (or delete the entry)")
            continue
        for k in hits:
            got = actual[k]
            if got != want_n:
                yield Finding(
                    "layout-contract-drift", HIGH,
                    f"{k}: compiled sharding {got} != declared {want_n} "
                    f"(contract entry {glob!r})",
                    where=k, subject=surface["name"],
                    remediation="fix the constraint at the declaration "
                                "site (distributed/mesh.py SpecLayout) or "
                                "update the contract if the new layout is "
                                "intended")


def _rule_replicated_large_buffer(surface, strict=False,
                                  min_bytes=REPLICATED_BUFFER_MIN_BYTES):
    """WARN (strict HIGH): a large input replicated over a shardable tp."""
    tp = int(surface.get("tp")
             or surface.get("mesh_axes", {}).get("tp", 1))
    if tp <= 1:
        return
    sev = HIGH if strict else WARN
    specs = surface.get("input_specs", {})
    for label, meta in sorted(surface.get("input_bytes", {}).items()):
        nbytes, shape = meta["bytes"], meta["shape"]
        if nbytes < min_bytes or _normalize_spec(specs.get(label)) != ():
            continue
        shardable = [i for i, d in enumerate(shape) if d and d % tp == 0]
        if not shardable:
            continue
        yield Finding(
            "replicated-large-buffer", sev,
            f"{label}: {fmt_bytes(nbytes)} {tuple(shape)} is fully "
            f"replicated over tp={tp} though dim(s) {shardable} divide tp "
            f"— {fmt_bytes(nbytes - nbytes // tp)} of HBM per chip bought "
            "back by sharding it",
            where=label, subject=surface["name"],
            remediation="give the buffer a SpecLayout axis (the adapter "
                        "bank shards on its rank or output dim) or record "
                        "here why replication is the better trade")


def _rule_dead_mesh_axis(mesh_axes, surfaces):
    """WARN: a declared axis no sharding in the program set uses."""
    if not mesh_axes:
        return
    used = set()
    for s in surfaces:
        for spec in list(s.get("input_specs", {}).values()) \
                + list(s.get("output_specs", {}).values()):
            for e in spec:
                for name in (e if isinstance(e, tuple) else (e,)):
                    if name:
                        used.add(name)
    names = ", ".join(s["name"] for s in surfaces)
    for axis in sorted(mesh_axes):
        if axis in used:
            continue
        yield Finding(
            "dead-mesh-axis", WARN,
            f"declared mesh axis {axis!r} (size {mesh_axes[axis]}) is used "
            f"by no input/output sharding across: {names}",
            subject=surfaces[0]["name"] if surfaces else "comms",
            remediation="drop the axis from the mesh, or wire it into a "
                        "SpecLayout placement (an axis that shards nothing "
                        "still fragments the device grid)")


def _rule_comms_over_budget(budget, subject="comms"):
    """HIGH: the tick's wire bytes cannot fit the tick wall at this ICI."""
    if budget is None or budget.ici_bytes_per_s is None:
        return                       # unknown interconnect: ungated, honest
    wire_s = budget.wire_time_s()
    if wire_s <= budget.tick_wall_s:
        return
    per = ", ".join(
        f"{e.name}={fmt_bytes(int(e.bytes_per_launch * e.launches_per_tick))}"
        for e in budget.estimates)
    yield Finding(
        "comms-over-budget", HIGH,
        f"{fmt_bytes(budget.bytes_per_tick)} on wire per tick needs "
        f"{wire_s * 1e3:.2f}ms at {fmt_bytes(int(budget.ici_bytes_per_s))}/s"
        f" per chip — over the {budget.tick_wall_s * 1e3:.2f}ms tick wall "
        f"before compute spends a FLOP ({per})",
        subject=subject,
        remediation="raise tp to shrink per-chip shards, cut the implicit "
                    "reshards above, or re-plan the tick "
                    "(fewer decode_steps per launch)")


# ====================================================== interconnect budget
@dataclasses.dataclass(frozen=True)
class CommsEstimate:
    """Per-launch wire bytes of one step program, and how often the
    scheduler launches it per tick."""
    name: str
    bytes_per_launch: int
    launches_per_tick: float = 1.0

    def to_json(self) -> dict:
        return {"name": self.name,
                "bytes_per_launch": int(self.bytes_per_launch),
                "launches_per_tick": float(self.launches_per_tick)}

    @classmethod
    def from_json(cls, obj) -> "CommsEstimate":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(obj) - known)
        if unknown:
            raise ValueError(f"unknown CommsEstimate fields {unknown}")
        return cls(**obj)


@dataclasses.dataclass(frozen=True)
class CommsBudget:
    """The DeploymentPlan's interconnect component (ISSUE-20): per-tick
    collective bytes against the chip's ICI and the tick wall. DISJOINT
    from the HBM residency components by construction — these are bytes
    *moved* per tick, not bytes *resident*, so they never enter
    ``components()`` or ``planned_total_bytes``."""
    tick_wall_s: float
    ici_bytes_per_s: float | None = None   # None = unknown (CPU): ungated
    estimates: tuple = ()

    @property
    def bytes_per_tick(self) -> int:
        return int(sum(e.bytes_per_launch * e.launches_per_tick
                       for e in self.estimates))

    def wire_time_s(self) -> float:
        if not self.ici_bytes_per_s:
            return 0.0
        return self.bytes_per_tick / float(self.ici_bytes_per_s)

    def share_of_tick(self):
        """Wire time as a fraction of the tick wall (None when the
        interconnect is unknown) — the bench ``comms_share_of_tick``."""
        if self.ici_bytes_per_s is None or not self.tick_wall_s:
            return None
        return self.wire_time_s() / self.tick_wall_s

    def to_json(self) -> dict:
        return {"tick_wall_s": float(self.tick_wall_s),
                "ici_bytes_per_s": self.ici_bytes_per_s,
                "estimates": [e.to_json() for e in self.estimates]}

    @classmethod
    def from_json(cls, obj) -> "CommsBudget":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(obj) - known)
        if unknown:
            raise ValueError(f"unknown CommsBudget fields {unknown}")
        kw = dict(obj)
        kw["estimates"] = tuple(CommsEstimate.from_json(e)
                                for e in kw.get("estimates", ()))
        return cls(**kw)


def smoke_comms_budget(surfaces, *, decode_steps=None,
                       ici_bytes_per_s=None) -> CommsBudget:
    """The zoo CommsBudget: every step surface launches once per tick; the
    tick wall is decode_steps x the default TPOT objective; the ICI is the
    running chip's (None off-accelerator, which un-gates the budget rule
    rather than inventing a number)."""
    if ici_bytes_per_s is None:
        import jax

        from ..observability.xla import device_ici_bandwidth

        try:
            ici_bytes_per_s = device_ici_bandwidth(jax.devices()[0])
        except Exception:
            ici_bytes_per_s = None
    steps = decode_steps
    if steps is None:
        steps = max([s.get("loop_steps", 1) for s in surfaces] or [1])
    return CommsBudget(
        tick_wall_s=steps * DEFAULT_TPOT_BUDGET_S,
        ici_bytes_per_s=ici_bytes_per_s,
        estimates=tuple(CommsEstimate(s["name"], s["bytes_per_launch"])
                        for s in surfaces))


# ============================================================= entry points
def analyze_comms_surfaces(surfaces, *, contract=None, expected=None,
                           mesh_axes=None, budget=None, strict=False,
                           allowlist=None, name="comms.surface") -> Report:
    """Run the five comms rules over a set of surfaces; returns the shared
    Report type (same gating as every other lint leg)."""
    import jax

    findings = []
    for s in surfaces:
        findings.extend(_rule_implicit_reshard(
            s, expected if expected is not None else default_expected()))
        per_contract = dict(contract or {})
        per_contract.update(s.get("contract", {}))
        if int(s.get("tp") or s.get("mesh_axes", {}).get("tp", 1)) > 1:
            findings.extend(_rule_layout_contract(s, per_contract))
        findings.extend(_rule_replicated_large_buffer(s, strict=strict))
    findings.extend(_rule_dead_mesh_axis(mesh_axes, surfaces))
    findings.extend(_rule_comms_over_budget(
        budget, subject=surfaces[0]["name"] if surfaces else "comms"))
    al = allowlist if allowlist is not None else BUILTIN_COMMS_ALLOWLIST
    try:
        backend = jax.default_backend()
    except Exception:
        backend = ""
    kept, suppressed = al.apply(findings, backend)
    return Report(name, kept, suppressed, tuple(COMMS_RULES))


def default_expected() -> dict:
    from ..distributed.mesh import SpecLayout

    return SpecLayout().expected_collectives()


def analyze_step_comms(allowlist=None, *, paths=None,
                       name="comms.surface", _surfaces=None) -> Report:
    """The ``comms_surface`` zoo entry body: compile the serving step
    programs under the tp serving mesh, inventory their collectives, check
    the SpecLayout contract, and run all five rules. ``--self-check``
    fails on any un-allowlisted HIGH here — an implicit reshard in the
    decode tick is a deploy blocker, not a curiosity. ``_surfaces`` lets
    the CLI reuse surfaces it already compiled for the printed table
    (three tp=2 compiles are the whole cost of this pass)."""
    from ..distributed.mesh import SpecLayout

    surfaces = (_surfaces if _surfaces is not None
                else step_comms_surfaces(paths=paths))
    layout = SpecLayout()
    for s in surfaces:
        s["contract"] = _OUTPUT_CONTRACT.get(s.get("path"), {})
    return analyze_comms_surfaces(
        surfaces,
        contract=layout.step_contract(),
        expected=layout.expected_collectives(),
        mesh_axes=surfaces[0]["mesh_axes"] if surfaces else None,
        budget=smoke_comms_budget(surfaces),
        allowlist=allowlist, name=name)


def sampled_logits_gather_surface(S=2, V=512, tp=None) -> dict:
    """The ONE documented collective of the split-KV decode path, in
    isolation: [S, V] logits vocab-sharded by the tied lm_head
    (SpecLayout.logits()), forced back to replicated the way sampling
    consumes them. The compiled surface must contain exactly one
    all-gather whose bytes-on-wire match S*V*itemsize*(tp-1)/tp — the
    acceptance pin that keeps the inventory's byte arithmetic honest."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from ..distributed.mesh import SpecLayout, serving_mesh

    if tp is None:
        tp = 2 if len(jax.devices()) >= 2 else 1
    mesh = serving_mesh(dp=1, tp=tp, set_global=False).jax_mesh
    layout = SpecLayout()

    @jax.jit
    def gather(logits):
        sharded = jax.lax.with_sharding_constraint(
            logits, NamedSharding(mesh, PartitionSpec(*layout.logits())))
        scaled = sharded * 2.0       # computed while vocab-sharded
        return jax.lax.with_sharding_constraint(
            scaled, NamedSharding(mesh, PartitionSpec()))

    args = (jnp.zeros((S, V), jnp.float32),)
    compiled = gather.lower(*args).compile()
    return compiled_comms_surface(
        compiled, name="sampled_logits_gather", labels=("logits",),
        args=args, mesh_axes={"dp": 1, "tp": tp})


# ------------------------------------------------------------- fixture mode
def comms_fixture_reports(path):
    """Seeded-violation mode for ``--comms PATH`` (mirrors --threads /
    --surface / --hbm): a ``.json`` file is a synthetic comms surface
    (keys: ``mesh_axes`` / ``contract`` / ``actual`` / ``collectives`` /
    ``buffers`` / ``budget`` / ``expected_collectives`` — all optional, a
    rule runs iff its section is present); a ``.py`` file is a PROGRAM
    fixture defining ``make_program() -> (fn, args)`` (optionally
    ``LOOP_STEPS``) that is compiled and inventoried for real. Directories
    run every fixture inside. Everything is strict with an empty
    allowlist: any HIGH exits 1."""
    if os.path.isdir(path):
        names = sorted(n for n in os.listdir(path)
                       if n.endswith((".py", ".json")))
        out = []
        for n in names:
            out.extend(comms_fixture_reports(os.path.join(path, n)))
        return out
    label = f"comms[{os.path.basename(path)}]"
    if path.endswith(".json"):
        with open(path, "r") as fh:
            spec = json.load(fh)
        return [_json_fixture_report(spec, label)]
    import runpy

    mod = runpy.run_path(path)
    if "make_program" not in mod:
        raise ValueError(f"{path}: a .py comms fixture must define "
                         "make_program() -> (fn, args)")
    import jax

    fn, args = mod["make_program"]()
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    compiled = fn.lower(*args).compile()
    surface = compiled_comms_surface(
        compiled, name=os.path.basename(path), args=args,
        loop_steps=int(mod.get("LOOP_STEPS", 1)))
    return [analyze_comms_surfaces(
        [surface], expected=mod.get("EXPECTED_COLLECTIVES", {}),
        strict=True, allowlist=Allowlist([]), name=label)]


def _json_fixture_report(spec, label) -> Report:
    name = spec.get("name", label)
    surface = {
        "name": name,
        "mesh_axes": dict(spec.get("mesh_axes", {})),
        "tp": spec.get("mesh_axes", {}).get("tp", 1),
        "ops": [],
        "input_specs": {k: _normalize_spec(v)
                        for k, v in spec.get("actual", {}).items()},
        "input_bytes": {},
        "output_specs": {},
        "contract": {},
        "bytes_per_launch": 0,
    }
    for c in spec.get("collectives", ()):
        dtype, nbytes = _hlo_result_bytes(c["result"])
        group = int(c.get("group_size", 1))
        count = int(c.get("count", 1))
        surface["ops"].append(CollectiveOp(
            kind=c["kind"], result=c["result"], dtype=dtype,
            buffer_bytes=nbytes, group_size=group, count=count,
            wire_bytes=bytes_on_wire(c["kind"], nbytes, group) * count,
            where=c.get("where", name)))
    surface["bytes_per_launch"] = sum(op.wire_bytes
                                      for op in surface["ops"])
    for b in spec.get("buffers", ()):
        import numpy as np

        nbytes = int(np.prod(b["shape"]) * np.dtype(b["dtype"]).itemsize)
        surface["input_bytes"][b["label"]] = {"bytes": nbytes,
                                              "shape": tuple(b["shape"])}
        surface["input_specs"].setdefault(
            b["label"], _normalize_spec(b.get("spec", ())))
    budget = None
    if "budget" in spec:
        budget = CommsBudget.from_json(spec["budget"])
    expected = spec.get("expected_collectives")
    if expected is not None:
        expected = {k: "declared by fixture" for k in expected}
    return analyze_comms_surfaces(
        [surface], contract=spec.get("contract"), expected=expected or {},
        mesh_axes=spec.get("mesh_axes") or None, budget=budget,
        strict=True, allowlist=Allowlist([]), name=label)


# Intentional, justified cross-chip traffic shipped with the repo — the
# lint's first catch, kept VISIBLE (Report.suppressed) until the layouts
# are fixed. Every entry is real wire traffic the declared SpecLayout does
# not explain; docs/ANALYSIS.md carries the full writeup.
BUILTIN_COMMS_ALLOWLIST = Allowlist([
    # The fused qkv projection is column-sharded as one 192-wide matrix
    # (q=64 | k=64 | v=64 at 4 heads x 16 dim): the tp=2 shard boundary at
    # 96 lands MID-k, so split_qkv's slices straddle shards and XLA patches
    # each layer with f32[S,1,hidden] collective-permutes (models/gpt.py
    # split_qkv). Known layout debt: the fix is interleaving per-shard head
    # groups so the shard boundary lands between heads, not inside them.
    AllowlistEntry(
        "implicit-reshard", subject="gpt.decode.*_tp",
        contains="models/gpt.py",
        reason="fused qkv column shard straddles the k/v head groups at "
               "tp=2 (shard boundary 96 falls inside k) — split_qkv's "
               "slices cross shards until per-shard head groups are "
               "interleaved; bounded, per-layer, hidden-sized traffic"),
    # Same straddle for the fused swiglu: gate|up halves of the 512-wide
    # gate_up projection each cross the 256-boundary column shard.
    AllowlistEntry(
        "implicit-reshard", subject="gpt.decode.*_tp",
        contains="incubate/nn/functional",
        reason="fused swiglu gate/up halves straddle the gate_up column "
               "shard at tp=2 — same head-group interleaving fix as "
               "split_qkv; bounded, per-layer, ffn-sized traffic"),
    # Top-k sampling over the vocab-sharded logits lowers to XLA's
    # distributed sort, which exchanges shard partitions with all-to-alls.
    # Intentional: sorting the shards in place moves O(S*k) bytes where
    # gathering the logits first would move O(S*V).
    AllowlistEntry(
        "implicit-reshard", subject="gpt.decode.*_tp", contains="sort",
        reason="top-k sampling sorts the vocab-sharded logits in place "
               "(distributed sort all-to-alls) — cheaper on wire than "
               "gathering [S, V] logits to every chip first"),
    # dp is the replica-FLEET axis: data parallelism lives at the
    # scheduler-replica level (ReplicaFleet), so no in-program sharding
    # ever names it — declared in the SpecLayout docstring, and kept
    # declared so fleet meshes and program meshes stay the same object.
    AllowlistEntry(
        "dead-mesh-axis", contains="'dp'",
        reason="dp is the replica-fleet axis (scheduler-level data "
               "parallelism, distributed/mesh.py SpecLayout): in-program "
               "shardings never use it by design"),
])
