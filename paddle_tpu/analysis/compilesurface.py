"""Compile-surface lint: the deployment's program inventory as a contract.

Every decode entry point in models/generation.py caches its compiled step
program under a ``cache_key`` tuple built immediately before the
``self._runner_for(cache_key, make_run)`` call. That construction IS the
deployment's compile surface: for a fixed serving configuration the set of
keys the runtime can ever request is finite and computable — unless a key
component is fed by a raw per-request value, in which case the inventory is
open and every novel value cold-compiles a whole program on live traffic
(the recompile-hazard rule's deployment-level sibling).

This pass makes that statically checkable:

1. **Key-schema extraction** — parse generation.py, find each tuple
   assigned to ``cache_key`` directly feeding a ``_runner_for`` call, and
   classify every component's provenance: ``literal`` (the path tag),
   ``shape`` (derived from an input array's shape/dtype — pinned by the
   serving layer's launch geometry), ``config`` (fed by a server-pinned
   parameter), ``bucketed`` (passed through a declared bounding function —
   any call whose name carries "bucket"), or ``request`` (a raw
   per-request scalar: the hazard).
2. **Closed inventory derivation** — a ``ServingConfig`` (slots, chunk
   width, decode steps, spec K, eos, pool signature, kernel) evaluates the
   extracted schemas into the exact cache keys a continuous-scheduler
   deployment can request; a ``ProgramManifest`` declares the keys the
   deployment commits to pre-compiling (inference/warmup.py AOTWarmup
   compiles exactly this manifest before /readyz reports ready).
3. **Rules** (on the shared Finding/Allowlist machinery):

   * ``manifest-incomplete`` (HIGH) — a runtime-constructible key is not
     covered by the manifest: it cold-compiles after readiness. The
     deploy gate.
   * ``unbounded-key``       (HIGH) — a key component is fed by a raw
     request-derived scalar; the inventory cannot be closed at all. Its
     first real catch was the dense ``generate()`` path keying on raw
     ``max_new_tokens`` (fixed by ``bucket_new_tokens``).
   * ``dead-bucket``         (WARN; HIGH in strict/fixture mode) — a
     manifest entry no analyzed config can request: warmup time and cache
     space with no traffic behind it.

The pass is pure AST + arithmetic — no jax import, no tracing — so it runs
in milliseconds and belongs in CI: ``python -m paddle_tpu.analysis
--self-check`` gates it (via the ``compile_surface`` zoo entry), ``--surface
PATH`` runs the seeded-fixture mode, and ``--manifest`` prints the derived
inventory as JSON. docs/ANALYSIS.md "Compile surface" has the full catalog.
"""
from __future__ import annotations

import ast
import dataclasses
import functools
import json
import math
import os

from .core import Report
from .findings import HIGH, WARN, Allowlist, AllowlistEntry, Finding

__all__ = [
    "SURFACE_RULES", "BUILTIN_SURFACE_ALLOWLIST", "CompileSurfaceError",
    "KeyComponent", "KeySchema", "ServingConfig", "ProgramManifest",
    "extract_key_schemas", "default_serving_configs", "default_manifest",
    "analyze_compile_surface", "surface_fixture_reports", "zoo_cross_check",
]

GENERATION_SOURCE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "models", "generation.py")

SURFACE_RULES = {
    "manifest-incomplete":
        "a runtime-constructible step-program cache key is not covered by "
        "the declared ProgramManifest — it cold-compiles on live traffic "
        "after /readyz (the deploy gate)",
    "unbounded-key":
        "a cache-key component is fed by a raw request-derived scalar with "
        "no declared bucket set — the program inventory is open and every "
        "novel value compiles a new program",
    "dead-bucket":
        "a manifest entry no analyzed serving configuration can request — "
        "warmup compiles it, nothing ever runs it",
}

# provenance kinds for key components
LITERAL = "literal"      # constant in the tuple (the path tag)
SHAPE = "shape"          # derived from an input array's .shape/.dtype
CONFIG = "config"        # fed by a server-pinned parameter
REQUEST = "request"      # fed by a raw per-request scalar (the hazard)
BUCKETED = "bucketed"    # passed through a declared bounding function

_RUNNER_CALL = "_runner_for"
_BOUNDING_MARKER = "bucket"     # call names containing it bound a component

# Which decode-entry parameters carry PER-REQUEST values at the API
# boundary (vs being pinned by server config). The whole-batch entry
# points (generate / generate_paged) are the public per-request decode
# API — clients pass their own budget and sampler knobs — while the step
# programs (prefill_chunk / decode_step / verify_step) only ever launch
# from the continuous scheduler's tick loop with config-pinned widths
# (inference/scheduler.py) and traced sampler inputs.
REQUEST_SCALARS = {
    "generate": ("max_new_tokens", "temperature", "top_k"),
    "generate_paged": ("max_new_tokens", "temperature", "top_k"),
}

# key-tag -> the zoo programs that lint its compiled form (analysis/zoo.py).
# zoo_cross_check() verifies this map against the live registry so a new
# decode path cannot ship without graph-lint coverage, and a renamed zoo
# entry cannot silently orphan a path.
ZOO_FAMILIES = {
    "dense": ("gpt_decode_dense",),
    "paged": ("gpt_decode_paged",),
    "prefill_chunk": ("gpt_prefill_chunk", "gpt_prefill_prefix",
                      "gpt_prefill_chunk_tp", "gpt_prefill_chunk_lora"),
    "decode_step": ("gpt_decode_step", "gpt_decode_step_tp",
                    "gpt_decode_step_lora"),
    "verify_step": ("gpt_verify_step", "gpt_verify_step_tp",
                    "gpt_verify_step_lora"),
}


class CompileSurfaceError(RuntimeError):
    """Schema extraction or key derivation cannot proceed (source drift)."""


# ---------------------------------------------------------------- extraction
@dataclasses.dataclass(frozen=True)
class KeyComponent:
    """One element of a cache_key tuple with its provenance."""
    index: int
    source: str          # ast.unparse of the component expression
    kind: str            # LITERAL | SHAPE | CONFIG | REQUEST | BUCKETED
    roots: tuple         # the parameter/attribute names it resolves to
    line: int


@dataclasses.dataclass(frozen=True)
class KeySchema:
    """The cache-key construction at one _runner_for call site."""
    path: str            # key tag ("prefill_chunk", ...) or "dense"
    method: str          # enclosing function name
    line: int            # line of the cache_key tuple
    components: tuple    # KeyComponent per tuple element

    @property
    def arity(self) -> int:
        return len(self.components)

    def request_components(self):
        return [c for c in self.components if c.kind == REQUEST]


def _ordered_stmts(body):
    """Flatten a function body into statement order, descending into
    compound statements (the cache_key assignments all live at the top
    level today, but fixtures may nest them)."""
    for stmt in body:
        yield stmt
        for attr in ("body", "orelse", "finalbody"):
            yield from _ordered_stmts(getattr(stmt, attr, []) or [])
        for handler in getattr(stmt, "handlers", []) or []:
            yield from _ordered_stmts(handler.body)


def _call_name(func):
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _roots(expr, env, params, _depth=0):
    """Resolve an expression to its provenance roots: a set of
    (kind, name) pairs where kind is 'param' | 'shape' | 'self' |
    'bucket' | 'global'. Purely syntactic — simple assignments are
    followed, everything else unions its children."""
    if _depth > 24 or expr is None:
        return set()
    if isinstance(expr, ast.Constant):
        return set()
    if isinstance(expr, ast.Name):
        if expr.id in env:
            kind, payload = env[expr.id]
            if kind == "shape":
                return {("shape", payload)}
            return _roots(payload, env, params, _depth + 1)
        if expr.id in params:
            return {("param", expr.id)}
        return {("global", expr.id)}
    if isinstance(expr, ast.Attribute):
        if expr.attr in ("shape", "dtype"):
            return {("shape", ast.unparse(expr.value))}
        if isinstance(expr.value, ast.Name) and expr.value.id == "self":
            return {("self", expr.attr)}
        return _roots(expr.value, env, params, _depth + 1)
    if isinstance(expr, ast.Call):
        name = _call_name(expr.func)
        if _BOUNDING_MARKER in name:
            # a declared bounding transform closes the component's domain
            # no matter what feeds it
            return {("bucket", name)}
        out = set()
        if isinstance(expr.func, ast.Attribute):
            out |= _roots(expr.func.value, env, params, _depth + 1)
        for a in list(expr.args) + [kw.value for kw in expr.keywords]:
            out |= _roots(a, env, params, _depth + 1)
        return out
    out = set()
    for child in ast.iter_child_nodes(expr):
        out |= _roots(child, env, params, _depth + 1)
    return out


def _component_kind(expr, roots, method):
    if isinstance(expr, ast.Constant):
        return LITERAL
    if any(k == "bucket" for k, _ in roots):
        return BUCKETED
    request = set(REQUEST_SCALARS.get(method, ()))
    if any(k == "param" and n in request for k, n in roots):
        return REQUEST
    if any(k == "shape" for k, _ in roots):
        return SHAPE
    return CONFIG


def extract_key_schemas(source=None):
    """Parse `source` (default: the installed models/generation.py) and
    return {path: KeySchema} for every ``cache_key = (...)`` tuple that
    feeds a ``_runner_for`` call. Raises CompileSurfaceError when a
    _runner_for call's key cannot be traced to a tuple literal — that is
    source drift the whole contract hangs on, not a findable."""
    path = source or GENERATION_SOURCE
    with open(path, "r") as fh:
        tree = ast.parse(fh.read(), filename=path)
    schemas = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        a = node.args
        params = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
        params.discard("self")
        env = {}              # name -> ("expr", node) | ("shape", src)
        tuples = {}           # name -> (Tuple node, lineno)
        for stmt in _ordered_stmts(node.body):
            if not isinstance(stmt, ast.Assign):
                continue
            tgt = stmt.targets[0] if len(stmt.targets) == 1 else None
            if isinstance(tgt, ast.Name):
                env[tgt.id] = ("expr", stmt.value)
                if isinstance(stmt.value, ast.Tuple):
                    tuples[tgt.id] = (stmt.value, stmt.lineno)
            elif isinstance(tgt, ast.Tuple) and all(
                    isinstance(e, ast.Name) for e in tgt.elts):
                # `B, P = ids.shape` style unpack: every target is
                # shape-derived when the RHS is a .shape access
                rhs_roots = _roots(stmt.value, env, params)
                is_shape = (isinstance(stmt.value, ast.Attribute)
                            and stmt.value.attr == "shape") or all(
                                k == "shape" for k, _ in rhs_roots)
                for e in tgt.elts:
                    if is_shape and rhs_roots:
                        env[e.id] = ("shape", ast.unparse(stmt.value))
                    else:
                        env[e.id] = ("expr", stmt.value)
            # the _runner_for site: Assign whose value calls _runner_for
            if (isinstance(stmt.value, ast.Call)
                    and _call_name(stmt.value.func) == _RUNNER_CALL
                    and stmt.value.args):
                key_arg = stmt.value.args[0]
                if not isinstance(key_arg, ast.Name):
                    raise CompileSurfaceError(
                        f"{path}:{stmt.lineno}: {_RUNNER_CALL} key is not a "
                        "name bound to a tuple literal")
                if key_arg.id not in tuples:
                    raise CompileSurfaceError(
                        f"{path}:{stmt.lineno}: no tuple assignment to "
                        f"{key_arg.id!r} precedes the {_RUNNER_CALL} call")
                tup, line = tuples[key_arg.id]
                comps = []
                for i, el in enumerate(tup.elts):
                    roots = _roots(el, env, params)
                    comps.append(KeyComponent(
                        index=i, source=ast.unparse(el),
                        kind=_component_kind(el, roots, node.name),
                        roots=tuple(sorted(f"{k}:{n}" for k, n in roots)),
                        line=line))
                tag = (tup.elts[0].value
                       if tup.elts and isinstance(tup.elts[0], ast.Constant)
                       and isinstance(tup.elts[0].value, str) else None)
                name = tag or ("dense" if node.name == "generate"
                               else node.name)
                if name in schemas:
                    name = f"{name}@{node.name}"
                schemas[name] = KeySchema(path=name, method=node.name,
                                          line=line, components=tuple(comps))
    return schemas


# ---------------------------------------------------------------- inventory
@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """One deployment's serving shape — everything the continuous
    scheduler pins about its step programs. ``kv_signature`` is
    PagedKVCache.signature(): (num_layers, num_kv_heads, head_dim,
    block_size, num_blocks, dtype)."""
    name: str = "continuous"
    slots: int = 8
    prefill_chunk: int = 16
    decode_steps: int = 4
    spec_k: int = 0
    eos_token_id: object = None
    max_seq_len: object = None          # None: the whole pool, one sequence
    kv_signature: tuple = (2, 4, 16, 128, 128, "bfloat16")
    decode_kernel: object = "pallas"
    ids_dtype: str = "int64"
    paths: tuple = ("prefill_chunk", "decode_step")
    # ISSUE-15 multi-LoRA: AdapterRegistry.signature() — ("lora", bank_rows,
    # r_max, n_target_paths) — when the deployment serves adapters, else
    # None (base programs, pre-adapter keys unchanged). The bank SHAPE is
    # the only adapter fact a cache key may carry: adapter mix/contents are
    # traced inputs, so churn can never fork programs.
    adapter_signature: object = None

    @property
    def block_size(self) -> int:
        return int(self.kv_signature[3])

    @property
    def pool_tokens(self) -> int:
        return int(self.kv_signature[3]) * int(self.kv_signature[4])

    @property
    def seq_capacity(self) -> int:
        return int(self.max_seq_len) if self.max_seq_len else self.pool_tokens

    @property
    def table_width(self) -> int:
        # PagedKVCache.blocks_for: max(1, ceil(seq / block_size))
        return max(1, math.ceil(self.seq_capacity / self.block_size))

    @property
    def eos(self) -> int:
        return -1 if self.eos_token_id is None else int(self.eos_token_id)

    def active_paths(self):
        paths = list(self.paths)
        if self.spec_k > 0 and "verify_step" not in paths:
            paths.append("verify_step")
        return tuple(paths)

    def program_keys(self, schemas=None):
        """The closed set of cache keys this deployment can request.
        Raises CompileSurfaceError on schema drift (arity/tag mismatch
        between the builders below and the extracted source)."""
        keys, errors = _derive(self, schemas or extract_key_schemas())
        if errors:
            raise CompileSurfaceError("; ".join(f.message for f in errors))
        return keys

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        out["kv_signature"] = list(self.kv_signature)
        out["paths"] = list(self.paths)
        if self.adapter_signature is not None:
            out["adapter_signature"] = list(self.adapter_signature)
        return out

    @classmethod
    def from_json(cls, obj) -> "ServingConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(obj) - known)
        if unknown:
            raise CompileSurfaceError(f"unknown ServingConfig fields "
                                      f"{unknown}; known: {sorted(known)}")
        kw = dict(obj)
        if "kv_signature" in kw:
            kw["kv_signature"] = tuple(kw["kv_signature"])
        if "paths" in kw:
            kw["paths"] = tuple(kw["paths"])
        if kw.get("adapter_signature") is not None:
            kw["adapter_signature"] = tuple(kw["adapter_signature"])
        return cls(**kw)


# per-path key builders; arity must match the extracted schema (drift gate)
_KEY_BUILDERS = {
    "prefill_chunk": (9, lambda c: (
        "prefill_chunk", c.slots, c.prefill_chunk, c.table_width,
        c.kv_signature, c.eos, c.ids_dtype, c.decode_kernel,
        c.adapter_signature)),
    "decode_step": (9, lambda c: (
        "decode_step", c.slots, c.decode_steps, c.table_width,
        c.kv_signature, c.eos, c.ids_dtype, c.decode_kernel,
        c.adapter_signature)),
    "verify_step": (8, lambda c: (
        "verify_step", c.slots, c.spec_k + 1, c.table_width,
        c.kv_signature, c.ids_dtype, c.decode_kernel,
        c.adapter_signature)),
}


def _derive(config, schemas):
    """(keys, findings) for one config: the concrete cache keys its active
    paths request, plus manifest-incomplete findings for paths whose key
    set cannot be closed (no builder, schema drift)."""
    keys, findings = [], []
    for path in config.active_paths():
        schema = schemas.get(path)
        if schema is None:
            findings.append(Finding(
                "manifest-incomplete", HIGH,
                f"config {config.name!r} activates path {path!r} but no "
                f"cache-key schema for it was extracted from the source",
                subject=f"{config.name}:{path}",
                remediation="fix the paths= list or the source under "
                            "analysis"))
            continue
        builder = _KEY_BUILDERS.get(path)
        if builder is None:
            findings.append(Finding(
                "manifest-incomplete", HIGH,
                f"config {config.name!r} activates path {path!r} whose key "
                "set has no closed-form builder: its shapes are "
                "request-derived (whole-batch API) — keep it off "
                "warmup-gated deployments or declare buckets for it",
                subject=f"{config.name}:{path}",
                remediation="serve through the continuous scheduler paths "
                            "(prefill_chunk/decode_step/verify_step)"))
            continue
        arity, build = builder
        if schema.arity != arity:
            findings.append(Finding(
                "manifest-incomplete", HIGH,
                f"key-schema drift on {path!r}: source builds "
                f"{schema.arity} components, the derivation expects "
                f"{arity} — the derived inventory would be wrong",
                where=f"{os.path.basename(GENERATION_SOURCE)}:{schema.line}",
                subject=f"{config.name}:{path}",
                remediation="update analysis/compilesurface.py "
                            "_KEY_BUILDERS next to the cache_key change"))
            continue
        keys.append(build(config))
    return tuple(keys), findings


def _freeze(key):
    if isinstance(key, (list, tuple)):
        return tuple(_freeze(k) for k in key)
    return key


@dataclasses.dataclass(frozen=True)
class ProgramManifest:
    """The declared program inventory: the cache keys a deployment commits
    to pre-compiling (AOTWarmup) and to never exceeding (this lint)."""
    name: str = "manifest"
    programs: tuple = ()

    @classmethod
    def from_configs(cls, configs, schemas=None,
                     name="derived") -> "ProgramManifest":
        schemas = schemas or extract_key_schemas()
        seen, out = set(), []
        for cfg in configs:
            for key in _derive(cfg, schemas)[0]:
                if key not in seen:
                    seen.add(key)
                    out.append(key)
        return cls(name=name, programs=tuple(out))

    def covers(self, key) -> bool:
        return _freeze(key) in {_freeze(p) for p in self.programs}

    def __contains__(self, key) -> bool:
        return self.covers(key)

    def to_json(self) -> dict:
        return {"name": self.name,
                "programs": [list(p) for p in self.programs]}

    @classmethod
    def from_json(cls, obj) -> "ProgramManifest":
        return cls(name=obj.get("name", "manifest"),
                   programs=tuple(_freeze(p) for p in obj["programs"]))


@functools.lru_cache(maxsize=1)
def default_serving_configs():
    """The deployment shapes the shipped serving defaults produce, at the
    zoo smoke pool geometry (analysis/zoo.py _continuous_smoke): the
    continuous scheduler's default knobs over the 2-layer GPT smoke pool,
    with and without speculative decoding, plus the multi-LoRA shape the
    zoo's adapter-indexed entries build (4 adapters + identity, rank 8,
    4 target projections on the 2-layer smoke GPT)."""
    base = ServingConfig(name="continuous-default")
    return (base,
            dataclasses.replace(base, name="continuous-spec", spec_k=4),
            dataclasses.replace(base, name="continuous-lora",
                                adapter_signature=("lora", 5, 8, 4)))


def default_manifest() -> ProgramManifest:
    return ProgramManifest.from_configs(default_serving_configs(),
                                        name="default-serving")


# --------------------------------------------------------------- the rules
# Findings the pass is right about but the code is right to keep: the
# paged whole-batch path keys on its sampler scalars and budget, which ARE
# per-request at the generate_paged API boundary — but the serving layer
# never feeds them request values (GenerateBatchingPredictor._run_batch
# pins max_new_tokens to the server cap and the fixed-batch path rejects
# per-request sampler knobs: supports_sampler_knobs=False). Visible
# suppressions, not a weakened rule.
_PAGED_PIN = ("the fixed-batch serving path pins this scalar: _run_batch "
              "passes the server-wide max_new_tokens cap and "
              "supports_sampler_knobs=False rejects per-request sampler "
              "headers (inference/serving.py), so one value per deployment "
              "reaches generate_paged")
BUILTIN_SURFACE_ALLOWLIST = Allowlist([
    AllowlistEntry("unbounded-key", subject="paged:max_new_tokens",
                   reason=_PAGED_PIN),
    AllowlistEntry("unbounded-key", subject="paged:greedy",
                   reason=_PAGED_PIN),
    AllowlistEntry("unbounded-key", subject="paged:float(temperature or "
                   "0.0)", reason=_PAGED_PIN),
    AllowlistEntry("unbounded-key", subject="paged:int(top_k or 0)",
                   reason=_PAGED_PIN),
])


def _key_subject(key) -> str:
    head = key[:3] if isinstance(key[0], str) else ("dense",) + tuple(key[:2])
    return ":".join(str(k) for k in head)


def analyze_compile_surface(configs=None, manifest=None, *, source=None,
                            allowlist=None, strict=False,
                            name="compile-surface") -> Report:
    """Run the compile-surface lint; returns the shared Report type.

    configs: ServingConfigs to derive inventories for. Default: the
        shipped default_serving_configs() — unless `source` points at a
        fixture file, in which case default is no configs (pure AST mode).
    manifest: the declared ProgramManifest. Default: derived from
        `configs` via the shipped schemas — i.e. the default self-check
        asserts the DEFAULT manifest is exactly closed over the default
        configs; fixtures pass a deliberately wrong one.
    strict: fixture/audit mode — dead-bucket escalates to HIGH so seeded
        violations gate the CLI exit code.
    """
    schemas = extract_key_schemas(source)
    rel = source or os.path.join("paddle_tpu", "models", "generation.py")
    if configs is None:
        configs = () if source is not None else default_serving_configs()

    findings = []
    for schema in schemas.values():
        for comp in schema.request_components():
            roots = [r.split(":", 1)[1] for r in comp.roots
                     if r.startswith("param:")]
            findings.append(Finding(
                "unbounded-key", HIGH,
                f"{schema.path} cache key [{comp.index}] `{comp.source}` is "
                f"fed by per-request scalar(s) {roots or comp.source} with "
                "no declared bucket set — every distinct value compiles a "
                "new whole program",
                where=f"{rel}:{comp.line}",
                subject=f"{schema.path}:{comp.source}",
                remediation="bucket the component to a declared set "
                            "(models/generation.py bucket_new_tokens) or "
                            "pin it at the serving layer"))

    derived = {}        # key -> [config names]
    for cfg in configs:
        keys, errs = _derive(cfg, schemas)
        findings.extend(errs)
        for k in keys:
            derived.setdefault(k, []).append(cfg.name)

    if manifest is None:
        manifest = ProgramManifest(name="derived", programs=tuple(derived))

    for key, names in derived.items():
        if not manifest.covers(key):
            findings.append(Finding(
                "manifest-incomplete", HIGH,
                f"runtime-constructible key {key} (config(s) "
                f"{', '.join(names)}) is not covered by manifest "
                f"{manifest.name!r} — it cold-compiles on live traffic "
                "after /readyz",
                where=rel, subject=_key_subject(key),
                remediation="add the program to the manifest (python -m "
                            "paddle_tpu.analysis --manifest prints the "
                            "derived inventory) or drop the config shape"))
    for key in manifest.programs:
        if _freeze(key) not in derived:
            findings.append(Finding(
                "dead-bucket", HIGH if strict else WARN,
                f"manifest program {key} is not derivable from any "
                "analyzed config — warmup compiles it, nothing requests it",
                where=manifest.name, subject=_key_subject(key),
                remediation="drop the stale bucket, or add the config "
                            "that needs it to the analyzed set"))

    al = allowlist if allowlist is not None else BUILTIN_SURFACE_ALLOWLIST
    kept, suppressed = al.apply(findings, backend="")
    return Report(name, kept, suppressed, tuple(SURFACE_RULES))


# ------------------------------------------------------------ fixture mode
def surface_fixture_reports(path):
    """Seeded-violation mode for ``--surface PATH``: a ``.py`` file is a
    generation-like source analyzed in pure AST mode; a ``.json`` file is
    {"configs": [...], "manifest": {...}, "source"?: "rel.py"}; a
    directory runs every such fixture inside it. Everything is strict."""
    if os.path.isdir(path):
        names = sorted(n for n in os.listdir(path)
                       if n.endswith((".py", ".json")))
        out = []
        for n in names:
            out.extend(surface_fixture_reports(os.path.join(path, n)))
        return out
    label = f"compile-surface[{os.path.basename(path)}]"
    if path.endswith(".json"):
        with open(path, "r") as fh:
            spec = json.load(fh)
        configs = tuple(ServingConfig.from_json(c)
                        for c in spec.get("configs", []))
        manifest = (ProgramManifest.from_json(spec["manifest"])
                    if "manifest" in spec else None)
        source = spec.get("source")
        if source is not None and not os.path.isabs(source):
            source = os.path.join(os.path.dirname(path), source)
        return [analyze_compile_surface(
            configs, manifest, source=source, strict=True,
            allowlist=Allowlist([]), name=label)]
    return [analyze_compile_surface(
        (), None, source=path, strict=True, allowlist=Allowlist([]),
        name=label)]


# ------------------------------------------------------------- zoo contract
def zoo_cross_check(schemas=None):
    """Verify ZOO_FAMILIES against the live zoo registry: every extracted
    key schema must have at least one registered zoo program linting its
    compiled form, and every decode-side zoo program must be claimed by
    exactly one family. Returns {path: (zoo programs,)}; raises
    CompileSurfaceError on a gap (a new decode path without lint coverage
    is a contract violation, not a finding)."""
    from .zoo import ZOO_PROGRAMS     # lazy: zoo imports this module

    schemas = schemas or extract_key_schemas()
    registered = set(ZOO_PROGRAMS)
    out = {}
    for path in schemas:
        family = ZOO_FAMILIES.get(path)
        if not family:
            raise CompileSurfaceError(
                f"decode path {path!r} has no zoo lint family — register "
                "its compiled program in analysis/zoo.py and map it in "
                "ZOO_FAMILIES")
        missing = [p for p in family if p not in registered]
        if missing:
            raise CompileSurfaceError(
                f"ZOO_FAMILIES[{path!r}] names unregistered zoo "
                f"program(s) {missing}")
        out[path] = family
    return out
