"""HBM residency lint: static peak-memory analysis + the deployment budget.

The paper's TPU-native design lives or dies on HBM residency (ROADMAP item
1: "tp sized by KV residency first") — yet until this pass nothing in the
repo could statically answer "will this ServingConfig fit on a chip?". Two
halves, same shape as every prior lint (hazard checkable before deploy):

1. **Liveness / peak-memory estimator** (`estimate_peak`) — the spirit of
   XLA's buffer-assignment liveness analysis run at the jaxpr level: walk
   the equations in schedule order tracking the live buffer set. Invars are
   held to their last use when donated (released to their output aliases)
   and to program end otherwise (the caller still owns them); consts and
   outvars are resident to the end; scan/while/cond bodies are analyzed
   recursively — scan/while carries are pinned live across their body so
   the old+new carry coexist (double buffering), cond takes the max over
   branches. The result is a per-program ``peak_bytes`` watermark, the
   top-K live buffers AT the peak with per-buffer provenance (the jaxpr
   equation's user frame), and a ``memory_stats``-shaped dict for the
   observability fallback (``estimated=True``).

   Known approximations (documented in docs/ANALYSIS.md): the walk uses
   the jaxpr's textual schedule (XLA may reorder), it never fuses (XLA's
   elementwise fusion elides temps the walk counts — an OVER-estimate),
   and nested-call donation frees inside the callee but not the caller's
   operand slot (a second over-estimate). Both biases are conservative:
   the static number errs toward "needs more HBM", which is the safe
   direction for a budget gate, and `estimate-drift` keeps it honest
   against the real ``CompiledMemoryStats`` wherever a backend has them.

2. **`DeploymentPlan`** — the per-chip residency contract for one
   ``ServingConfig`` (reusing the ISSUE-13 config → program-inventory
   derivation): params/tp (optimizer-free serving state), the
   ``PagedKVCache`` pool per chip, a prefix-cache parked tier carved out
   of the pool, the max static temp peak across every manifest
   program, and (ISSUE-15) the resident multi-LoRA adapter banks — all
   evaluated against a declared chip HBM budget with headroom.

Rules (shared Finding/Allowlist machinery):

* ``hbm-over-budget`` (HIGH) — planned residency exceeds
  budget × (1 − headroom): the replica OOMs or swaps before it serves.
* ``estimate-drift``   (HIGH) — static peak vs the compiled program's
  ``memory_stats().peak_bytes`` diverge beyond tolerance where real stats
  exist. The estimator is self-validating: drift means the plan's temp
  numbers are fiction, not that the chip is fine.
* ``oversized-temp``   (WARN; HIGH in strict/fixture mode) — one live
  buffer at a program's peak exceeds 25% of the budget: a remat/chunking
  opportunity, and the classic giant-broadcast footgun.
* ``pool-misfit``      (WARN; HIGH in strict/fixture mode) — the pool
  cannot cover ``max_slots × blocks_for(max_seq_len)`` (requests queue on
  blocks at exactly full concurrency), or >30% of the pool is unreachable
  by any admissible request (HBM bought, never used).

Gating: ``python -m paddle_tpu.analysis --self-check`` runs the
``hbm_residency`` zoo entry (smoke GPT step programs + the smoke pool
against a smoke budget, drift-checked against real stats where the backend
provides them); ``--hbm [NAME|FILE.json]`` prints the residency table (the
deploy-review artifact) or runs seeded fixtures strict; ``plan_kv_pool``
is the runtime half — the continuous scheduler's ``hbm_budget=`` knob
sizes its pool from the plan and publishes
``paddle_hbm_planned_bytes{component=params|kv_pool|prefix_tier|temps|``
``adapter_bank}`` next to ``paddle_hbm_budget_bytes`` so a scrape shows
plan vs actual.
"""
from __future__ import annotations

import dataclasses
import json
import math
import os

from .core import Report, aval_bytes, fmt_bytes, source_of, _sub_jaxprs
from .findings import HIGH, WARN, Allowlist, Finding

__all__ = [
    "HBM_RULES", "BUILTIN_HBM_ALLOWLIST", "PeakEstimate", "ProgramEstimate",
    "DeploymentPlan", "estimate_peak", "estimate_memory_stats",
    "analyze_hbm_plan", "plan_kv_pool", "params_bytes_of",
    "blocks_for", "per_block_bytes", "smoke_plan", "smoke_budget_bytes",
    "hbm_fixture_reports", "analyze_hbm_residency",
]

HBM_RULES = {
    "hbm-over-budget":
        "the planned per-chip residency (params/tp + KV pool + prefix tier "
        "+ max program temp peak) exceeds budget x (1 - headroom) — the "
        "replica OOMs or thrashes before it serves",
    "estimate-drift":
        "the static peak estimate and the compiled program's real "
        "memory_stats().peak_bytes diverge beyond tolerance — the plan's "
        "numbers are fiction until the estimator (or the trace) is fixed",
    "oversized-temp":
        "one live buffer at a program's static peak exceeds 25% of the "
        "budget — a remat/chunking opportunity (HIGH in strict mode)",
    "pool-misfit":
        "the KV pool cannot cover max_slots x blocks_for(max_seq_len), or "
        ">30% of its blocks are unreachable by any admissible request",
}

DEFAULT_HEADROOM = 0.08           # fragmentation + allocator slack
OVERSIZED_TEMP_FRACTION = 0.25
POOL_WASTE_FRACTION = 0.30
# estimate-drift gate: the walk never fuses and XLA reorders, so agreement
# is order-of-magnitude, not byte-exact. Static must land within
# [real/(1+tol), real*(1+tol)] (tol=1.0: within 2x either way) above a
# 1 MiB absolute floor — forgetting the KV pool arguments (the dominant
# serving bytes) or double-counting a scan still blows this wide open.
DRIFT_REL_TOL = 1.0
DRIFT_ABS_FLOOR = 1 << 20

# The hbm allowlist ships EMPTY on purpose: the zoo residency entry is
# expected to be clean with no explained exceptions (unlike the donation/
# paged-key lists). It exists so fixture/CLI plumbing and the stale-entry
# audit treat all four lints uniformly.
BUILTIN_HBM_ALLOWLIST = Allowlist([])


# ===================================================================== walk
def _is_var(v):
    import jax

    return isinstance(v, jax.core.Var) and not isinstance(v, jax.core.DropVar)


class _Buf:
    """One live buffer during the walk: bytes + provenance for the top-K
    breakdown. ``kind``: argument | const | temp | output | internal."""

    __slots__ = ("label", "bytes", "where", "kind")

    def __init__(self, label, nbytes, where, kind):
        self.label = label
        self.bytes = int(nbytes)
        self.where = where
        self.kind = kind

    def to_dict(self):
        return {"label": self.label, "bytes": self.bytes,
                "where": self.where, "kind": self.kind}


class PeakEstimate:
    """The estimator's verdict on one program. ``at_peak`` is the live set
    snapshot (top-K by bytes) at the watermark; ``peak_bytes_undonated``
    re-runs the walk with donation ignored — the number to compare against
    a backend that does not implement donation (CPU keeps both copies, so
    its real stats match the undonated walk, not the donated one)."""

    __slots__ = ("name", "peak_bytes", "peak_bytes_undonated",
                 "argument_bytes", "output_bytes", "alias_bytes",
                 "temp_bytes", "at_peak", "eqn_count")

    def __init__(self, name, peak_bytes, peak_bytes_undonated,
                 argument_bytes, output_bytes, alias_bytes, temp_bytes,
                 at_peak, eqn_count):
        self.name = name
        self.peak_bytes = int(peak_bytes)
        self.peak_bytes_undonated = int(peak_bytes_undonated)
        self.argument_bytes = int(argument_bytes)
        self.output_bytes = int(output_bytes)
        self.alias_bytes = int(alias_bytes)
        self.temp_bytes = int(temp_bytes)
        self.at_peak = tuple(at_peak)
        self.eqn_count = int(eqn_count)

    @property
    def largest_temp(self):
        """(label, bytes, where) of the biggest non-argument buffer live at
        the peak, or None — the oversized-temp rule's subject."""
        temps = [b for b in self.at_peak if b.kind in ("temp", "internal")]
        if not temps:
            return None
        top = max(temps, key=lambda b: b.bytes)
        return (top.label, top.bytes, top.where)

    def to_memory_stats(self) -> dict:
        """The observability/xla.py ``memory_stats`` shape, estimated."""
        return {
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "generated_code_bytes": 0,
            "alias_bytes": self.alias_bytes,
            "peak_bytes": self.peak_bytes,
            "estimated": True,
        }

    def to_dict(self) -> dict:
        out = self.to_memory_stats()
        out.update({
            "name": self.name,
            "peak_bytes_undonated": self.peak_bytes_undonated,
            "eqn_count": self.eqn_count,
            "at_peak": [b.to_dict() for b in self.at_peak],
        })
        return out


def _unwrap_single_pjit(closed_jaxpr, donated):
    """make_jaxpr over a jitted fn yields one pjit eqn wrapping the real
    program; analyze the inner jaxpr so donation has its aliasing effect
    (an outer walk would hold every operand across the one eqn and
    donation could never release anything). Mirrors core.analyze's
    donation extraction off the pjit params."""
    import jax

    jaxpr = closed_jaxpr.jaxpr
    eqns = jaxpr.eqns
    if (donated is None and len(eqns) == 1
            and eqns[0].primitive.name == "pjit"
            and set(map(id, eqns[0].invars)) == set(map(id, jaxpr.invars))):
        inner = eqns[0].params.get("jaxpr")
        flags = eqns[0].params.get("donated_invars")
        if isinstance(inner, jax.core.ClosedJaxpr) and flags is not None:
            return inner, tuple(flags)
    return closed_jaxpr, donated


def _estimate_open(jaxpr, const_bytes, donated, pinned, arg_names, top_k,
                   depth=0):
    """Schedule-order liveness walk over one (open) jaxpr.

    Returns (peak_bytes, snapshot, entry_bytes): ``entry_bytes`` is the
    resident set at entry (invars + consts) — recursion subtracts it so an
    equation's "internal extra" never double-counts operands already live
    in the caller's scope."""
    eqns = jaxpr.eqns
    last = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if _is_var(v):
                last[v] = i
    outset = {v for v in jaxpr.outvars if _is_var(v)}
    donated = tuple(donated or ())
    donated_set = {v for i, v in enumerate(jaxpr.invars)
                   if i < len(donated) and donated[i] and _is_var(v)}
    consts = set(jaxpr.constvars)

    live: dict = {}
    running = 0

    def _add(v, label, where, kind):
        nonlocal running
        if v in live:
            return
        b = aval_bytes(v.aval)
        if b <= 0:
            return
        live[v] = _Buf(label, b, where, kind)
        running += b

    for i, v in enumerate(jaxpr.invars):
        label = (arg_names[i] if arg_names and i < len(arg_names)
                 else f"arg[{i}]")
        _add(v, label, "", "argument")
    for i, v in enumerate(jaxpr.constvars):
        b = const_bytes[i] if i < len(const_bytes) else aval_bytes(v.aval)
        if v not in live and b > 0:
            live[v] = _Buf(f"const[{i}]", b, "", "const")
            running += b
    entry_bytes = running

    peak = running
    snapshot = list(live.values())
    invar_set = set(jaxpr.invars)

    for i, eqn in enumerate(eqns):
        out_bufs = []
        where = source_of(eqn)
        for o in eqn.outvars:
            if not _is_var(o) or o in live:
                continue
            b = aval_bytes(o.aval)
            if b > 0:
                kind = "output" if o in outset else "temp"
                out_bufs.append((o, _Buf(eqn.primitive.name, b, where,
                                         kind)))
        extra = _inner_extra(eqn, depth)
        working = running + sum(b.bytes for _, b in out_bufs) + extra
        if working > peak:
            peak = working
            snapshot = list(live.values()) + [b for _, b in out_bufs]
            if extra > 0:
                snapshot.append(_Buf(f"{eqn.primitive.name}:internal",
                                     extra, where, "internal"))
        for o, buf in out_bufs:
            live[o] = buf
            running += buf.bytes
        for v in {v for v in eqn.invars if _is_var(v)}:
            if last.get(v) != i or v not in live:
                continue
            if v in outset or v in pinned or v in consts:
                continue
            if v in invar_set and v not in donated_set:
                continue                # caller still owns the buffer
            running -= live.pop(v).bytes
    return peak, snapshot, entry_bytes


def _inner_extra(eqn, depth):
    """Bytes an equation holds BEYOND its operands and results: the inner
    temp watermark of its sub-jaxprs. Alternatives (cond branches, while
    cond/body) never run concurrently, so the max is taken; scan/while
    carries are pinned inside their body — the body's new-carry outputs
    then coexist with the pinned old carry, which is exactly the
    double-buffering XLA's loop lowering pays."""
    import jax

    if depth > 24:
        return 0
    subs = _sub_jaxprs(eqn.params)
    if not subs:
        return 0
    name = eqn.primitive.name
    extras = [0]
    for _tag, sub in subs:
        if isinstance(sub, jax.core.ClosedJaxpr):
            open_j = sub.jaxpr
            const_bytes = [getattr(c, "nbytes", aval_bytes(v.aval))
                           for v, c in zip(open_j.constvars, sub.consts)]
        else:
            open_j = sub
            const_bytes = []
        donated = ()
        if name == "pjit":
            flags = eqn.params.get("donated_invars")
            if flags is not None:
                donated = tuple(flags)
        pinned = frozenset()
        if name == "scan":
            nc = int(eqn.params.get("num_consts", 0))
            ncar = int(eqn.params.get("num_carry", 0))
            pinned = frozenset(v for v in open_j.invars[nc:nc + ncar]
                               if _is_var(v))
        elif name == "while":
            pinned = frozenset(v for v in open_j.invars if _is_var(v))
        sub_peak, _snap, sub_entry = _estimate_open(
            open_j, const_bytes, donated, pinned, None, 0, depth + 1)
        extras.append(max(0, sub_peak - sub_entry) + sum(const_bytes))
    return max(extras)


def estimate_peak(closed_jaxpr, *, donated=None, arg_names=None,
                  name="program", top_k=8) -> PeakEstimate:
    """Statically estimate the HBM watermark of one traced program.

    ``donated``: per-invar flags; when omitted and the program is a single
    jitted call, the flags are read off its pjit equation (same extraction
    as core.analyze). ``top_k`` bounds the at-peak breakdown."""
    import jax

    inner, donated = _unwrap_single_pjit(closed_jaxpr, donated)
    if isinstance(inner, jax.core.ClosedJaxpr):
        open_j = inner.jaxpr
        const_bytes = [getattr(c, "nbytes", aval_bytes(v.aval))
                       for v, c in zip(open_j.constvars, inner.consts)]
    else:
        open_j = inner
        const_bytes = []
    donated = tuple(donated or ())
    peak, snapshot, _entry = _estimate_open(
        open_j, const_bytes, donated, frozenset(), arg_names, top_k)
    if any(donated):
        undonated, _, _ = _estimate_open(
            open_j, const_bytes, (), frozenset(), arg_names, top_k)
    else:
        undonated = peak
    argument = sum(aval_bytes(v.aval) for v in open_j.invars)
    seen = set()
    output = 0
    for v in open_j.outvars:
        if _is_var(v) and v not in seen:
            seen.add(v)
            output += aval_bytes(v.aval)
    alias = sum(aval_bytes(v.aval) for i, v in enumerate(open_j.invars)
                if i < len(donated) and donated[i])
    at_peak = sorted(snapshot, key=lambda b: -b.bytes)[:top_k]
    temp = sum(b.bytes for b in snapshot
               if b.kind in ("temp", "internal"))
    return PeakEstimate(name, peak, undonated, argument, output, alias,
                        temp, at_peak, len(open_j.eqns))


def estimate_memory_stats(closed_jaxpr=None, *, compiled=None, donated=None,
                          name="program") -> dict:
    """``memory_stats``-shaped dict from the static estimator, for backends
    with no ``CompiledMemoryStats`` (observability/xla.py falls back here).

    Full tier with a jaxpr; degraded tier from a compiled executable's
    aval/donation metadata alone (``args_info``) — argument + output bytes
    with temps unknown, still non-zero where the real stats read zero.
    ``{}`` when neither source yields anything."""
    if closed_jaxpr is not None:
        return estimate_peak(closed_jaxpr, donated=donated,
                             name=name).to_memory_stats()
    if compiled is None:
        return {}
    argument = output = alias = 0
    try:
        infos = compiled.args_info
        flat = []
        for entry in (infos if isinstance(infos, tuple) else (infos,)):
            if isinstance(entry, dict):
                flat.extend(entry.values())
            elif isinstance(entry, (list, tuple)):
                flat.extend(entry)
            else:
                flat.append(entry)
        for info in flat:
            aval = getattr(info, "_aval", None) or getattr(info, "aval",
                                                           None)
            b = aval_bytes(aval) if aval is not None else 0
            argument += b
            if getattr(info, "donated", False):
                alias += b
    except Exception:
        argument = alias = 0
    try:
        out_avals = getattr(compiled, "out_avals", None)
        if not out_avals:       # jax 0.4.x: avals live on the executable
            out_avals = getattr(getattr(compiled, "_executable", None),
                                "out_avals", None)
        if out_avals:
            output = sum(aval_bytes(a) for a in out_avals)
    except Exception:
        output = 0
    if argument <= 0 and output <= 0:
        return {}
    return {
        "argument_bytes": argument,
        "output_bytes": output,
        "temp_bytes": 0,
        "generated_code_bytes": 0,
        "alias_bytes": alias,
        "peak_bytes": max(0, argument + output - alias),
        "estimated": True,
    }


# ================================================================= the plan
def blocks_for(seq_len, block_size) -> int:
    """PagedKVCache.blocks_for, pool-free (plan-time arithmetic)."""
    return max(1, math.ceil(int(seq_len) / int(block_size)))


def per_block_bytes(kv_signature, tp=1) -> int:
    """Per-chip bytes one pool block costs across k+v and all layers:
    2 * layers * (kv_heads/tp) * block_size * head_dim * itemsize —
    must agree with PagedKVCache.per_chip_pool_bytes()/num_blocks (the
    plan/pool parity test pins this)."""
    import jax.numpy as jnp

    layers, kv_heads, head_dim, block_size, _nb, dtype = kv_signature
    tp = max(1, int(tp))
    heads = int(kv_heads) / tp if int(kv_heads) % tp == 0 else int(kv_heads)
    return int(2 * int(layers) * heads * int(block_size) * int(head_dim)
               * jnp.dtype(dtype).itemsize)


@dataclasses.dataclass(frozen=True)
class ProgramEstimate:
    """One manifest program's contribution to the plan: the static peak /
    temp watermark (estimator), the largest single live buffer at the peak
    (oversized-temp's subject), and the real compiled peak where the
    backend provided one (estimate-drift's other hand)."""
    name: str
    peak_bytes: int
    temp_bytes: int
    largest_label: str = ""
    largest_bytes: int = 0
    largest_where: str = ""
    measured_peak_bytes: object = None      # int | None

    @classmethod
    def from_estimate(cls, est: PeakEstimate,
                      measured=None) -> "ProgramEstimate":
        top = est.largest_temp or ("", 0, "")
        return cls(name=est.name, peak_bytes=est.peak_bytes,
                   temp_bytes=est.temp_bytes, largest_label=top[0],
                   largest_bytes=top[1], largest_where=top[2],
                   measured_peak_bytes=measured)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, obj) -> "ProgramEstimate":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(obj) - known)
        if unknown:
            raise ValueError(f"unknown ProgramEstimate fields {unknown}; "
                             f"known: {sorted(known)}")
        return cls(**obj)


@dataclasses.dataclass(frozen=True)
class DeploymentPlan:
    """Per-chip HBM residency for one ServingConfig against a budget.

    Components are DISJOINT so they sum to ``planned_total_bytes``:
    ``prefix_blocks`` is carved OUT of the pool (parked prefix blocks are
    pool blocks — reserving them in the plan keeps the kv_pool number
    honest about blocks actually available to live requests)."""
    config: object                       # compilesurface.ServingConfig
    budget_bytes: int
    headroom: float = DEFAULT_HEADROOM
    params_bytes: int = 0                # FULL params; the plan divides by tp
    tp: int = 1
    prefix_blocks: int = 0
    programs: tuple = ()                 # ProgramEstimate per manifest entry
    temps_bytes: int = 0                 # declared floor when no programs
    adapter_bank_bytes: int = 0          # ISSUE-15: resident LoRA banks
    # ISSUE-20: the interconnect component (comms.CommsBudget or None).
    # DISJOINT from components() by construction: these are bytes MOVED
    # per tick, not bytes resident, so they never enter the residency sum
    # (which tests pin as == sum(components)) — they get their own rows in
    # render_table and their own rule (comms-over-budget).
    comms: object = None

    def __post_init__(self):
        if self.budget_bytes <= 0:
            raise ValueError("budget_bytes must be positive")
        if not 0 <= self.headroom < 1:
            raise ValueError("headroom must be in [0, 1)")
        if self.prefix_blocks > self.num_blocks:
            raise ValueError(f"prefix_blocks {self.prefix_blocks} exceeds "
                             f"the pool ({self.num_blocks} blocks)")

    # ------------------------------------------------------------ geometry
    @property
    def num_blocks(self) -> int:
        return int(self.config.kv_signature[4])

    @property
    def per_block_bytes(self) -> int:
        return per_block_bytes(self.config.kv_signature, tp=self.tp)

    @property
    def usable_bytes(self) -> int:
        return int(self.budget_bytes * (1.0 - self.headroom))

    # ---------------------------------------------------------- components
    @property
    def params_component(self) -> int:
        return int(self.params_bytes) // max(1, int(self.tp))

    @property
    def kv_pool_component(self) -> int:
        return (self.num_blocks - self.prefix_blocks) * self.per_block_bytes

    @property
    def prefix_tier_component(self) -> int:
        return self.prefix_blocks * self.per_block_bytes

    @property
    def temps_component(self) -> int:
        temps = [p.temp_bytes for p in self.programs]
        return max([int(self.temps_bytes)] + temps)

    @property
    def adapter_bank_component(self) -> int:
        # the full fixed-shape banks (AdapterRegistry.bank_bytes()) — HBM
        # is paid for A_max slots up front whether or not they're loaded,
        # which is exactly why the plan must carry it (ISSUE-15)
        return int(self.adapter_bank_bytes)

    def components(self) -> dict:
        return {
            "params": self.params_component,
            "kv_pool": self.kv_pool_component,
            "prefix_tier": self.prefix_tier_component,
            "temps": self.temps_component,
            "adapter_bank": self.adapter_bank_component,
        }

    @property
    def planned_total_bytes(self) -> int:
        return sum(self.components().values())

    # -------------------------------------------------------------- io/ui
    def to_json(self) -> dict:
        return {
            "config": self.config.to_json(),
            "budget_bytes": int(self.budget_bytes),
            "headroom": float(self.headroom),
            "params_bytes": int(self.params_bytes),
            "tp": int(self.tp),
            "prefix_blocks": int(self.prefix_blocks),
            "programs": [p.to_json() for p in self.programs],
            "temps_bytes": int(self.temps_bytes),
            "adapter_bank_bytes": int(self.adapter_bank_bytes),
            "comms": self.comms.to_json() if self.comms else None,
            "components": self.components(),
            "planned_total_bytes": self.planned_total_bytes,
        }

    @classmethod
    def from_json(cls, obj) -> "DeploymentPlan":
        from .compilesurface import ServingConfig

        known = {f.name for f in dataclasses.fields(cls)}
        derived = {"components", "planned_total_bytes"}
        unknown = sorted(set(obj) - known - derived)
        if unknown:
            raise ValueError(f"unknown DeploymentPlan fields {unknown}; "
                             f"known: {sorted(known)}")
        kw = {k: v for k, v in obj.items() if k in known}
        kw["config"] = ServingConfig.from_json(kw["config"])
        kw["programs"] = tuple(ProgramEstimate.from_json(p)
                               for p in kw.get("programs", ()))
        if kw.get("comms") is not None:
            from .comms import CommsBudget

            kw["comms"] = CommsBudget.from_json(kw["comms"])
        return cls(**kw)

    def render_table(self) -> str:
        """The deploy-review artifact ``--hbm`` prints: one row per
        component with its share of the budget, then the per-program
        static/measured peaks."""
        total = self.planned_total_bytes
        fit = "FIT" if total <= self.usable_bytes else "OVER"
        lines = [
            f"== hbm residency: {self.config.name} ==",
            f"  budget {fmt_bytes(self.budget_bytes):>12s}   headroom "
            f"{self.headroom:.0%}   usable {fmt_bytes(self.usable_bytes)}"
            f"   tp={self.tp}",
        ]
        for comp, nbytes in self.components().items():
            pct = 100.0 * nbytes / self.budget_bytes
            lines.append(f"  {comp:12s} {fmt_bytes(nbytes):>12s}  "
                         f"{pct:5.1f}% of budget")
        lines.append(f"  {'total':12s} {fmt_bytes(total):>12s}  "
                     f"{100.0 * total / self.budget_bytes:5.1f}% -> {fit}")
        if self.comms is not None:
            share = self.comms.share_of_tick()
            wall_ms = self.comms.tick_wall_s * 1e3
            lines.append(
                f"  {'comms':12s} {fmt_bytes(self.comms.bytes_per_tick):>12s}"
                + ("  on wire/tick, interconnect unknown (un-gated)"
                   if share is None else
                   f"  on wire/tick = {share:6.1%} of the {wall_ms:.0f}ms "
                   "tick wall"))
        for p in self.programs:
            measured = (fmt_bytes(p.measured_peak_bytes)
                        if p.measured_peak_bytes else "n/a")
            lines.append(f"  program {p.name}: static peak "
                         f"{fmt_bytes(p.peak_bytes)} (temps "
                         f"{fmt_bytes(p.temp_bytes)}), measured {measured}")
        return "\n".join(lines)


# ================================================================ the rules
def _rule_over_budget(plan):
    total, usable = plan.planned_total_bytes, plan.usable_bytes
    if total <= usable:
        return
    comps = ", ".join(f"{k}={fmt_bytes(v)}"
                      for k, v in plan.components().items())
    yield Finding(
        "hbm-over-budget", HIGH,
        f"planned residency {fmt_bytes(total)} exceeds the usable budget "
        f"{fmt_bytes(usable)} ({fmt_bytes(plan.budget_bytes)} x "
        f"(1 - {plan.headroom:.0%}) headroom): {comps}",
        subject=f"{plan.config.name}:plan",
        remediation="shrink the pool (plan_kv_pool sizes it to fit), raise "
                    "tp, quantize the KV dtype, or declare a bigger chip")


def _rule_estimate_drift(plan, rel_tol=DRIFT_REL_TOL,
                         abs_floor=DRIFT_ABS_FLOOR):
    for p in plan.programs:
        real = p.measured_peak_bytes
        if not real:
            continue                    # no stats on this backend: ungated
        static = int(p.peak_bytes)
        real = int(real)
        lo = real / (1.0 + rel_tol)
        hi = real * (1.0 + rel_tol)
        if lo <= static <= hi or abs(static - real) <= abs_floor:
            continue
        yield Finding(
            "estimate-drift", HIGH,
            f"program {p.name!r}: static peak {fmt_bytes(static)} vs "
            f"compiled memory_stats peak {fmt_bytes(real)} — outside the "
            f"{rel_tol:+.0%} tolerance; the estimator (or this trace) is "
            "lying and every residency number downstream is suspect",
            subject=f"{plan.config.name}:{p.name}",
            remediation="re-derive the program estimate from the deployed "
                        "trace, or fix analysis/hbm.py estimate_peak")


def _rule_oversized_temp(plan, strict=False):
    sev = HIGH if strict else WARN
    cap = int(OVERSIZED_TEMP_FRACTION * plan.budget_bytes)
    for p in plan.programs:
        if p.largest_bytes <= cap:
            continue
        yield Finding(
            "oversized-temp", sev,
            f"program {p.name!r} materializes a single "
            f"{fmt_bytes(p.largest_bytes)} buffer ({p.largest_label}) at "
            f"its peak — over {OVERSIZED_TEMP_FRACTION:.0%} of the "
            f"{fmt_bytes(plan.budget_bytes)} budget",
            where=p.largest_where,
            subject=f"{plan.config.name}:{p.name}",
            remediation="chunk or remat the producing op (a broadcast this "
                        "size usually wants to stay fused or be tiled)")


def _rule_pool_misfit(plan, strict=False):
    sev = HIGH if strict else WARN
    cfg = plan.config
    live_blocks = plan.num_blocks - plan.prefix_blocks
    if cfg.max_seq_len:
        need = cfg.slots * blocks_for(cfg.max_seq_len, cfg.block_size)
        if need > live_blocks:
            yield Finding(
                "pool-misfit", sev,
                f"{cfg.slots} slots x blocks_for({cfg.max_seq_len}) = "
                f"{need} blocks exceed the {live_blocks} live pool blocks "
                f"({plan.num_blocks} - {plan.prefix_blocks} parked) — full "
                "concurrency at max length queues on blocks",
                subject=f"{cfg.name}:pool",
                remediation="grow num_blocks, shrink max_seq_len/slots, or "
                            "accept admission-time deferrals")
            return
    reachable = cfg.slots * cfg.table_width + plan.prefix_blocks
    unreachable = max(0, plan.num_blocks - reachable)
    if unreachable > POOL_WASTE_FRACTION * plan.num_blocks:
        yield Finding(
            "pool-misfit", sev,
            f"{unreachable} of {plan.num_blocks} pool blocks "
            f"({unreachable / plan.num_blocks:.0%}) are unreachable by any "
            f"admissible request ({cfg.slots} slots x table_width "
            f"{cfg.table_width} + {plan.prefix_blocks} parked) — HBM "
            "bought, never used",
            subject=f"{cfg.name}:pool",
            remediation="shrink num_blocks (plan_kv_pool clamps to the "
                        "reachable set), raise slots/max_seq_len, or park "
                        "the excess as prefix tier")


def analyze_hbm_plan(plan, *, strict=False, allowlist=None,
                     name=None) -> Report:
    """Run the four residency rules over one DeploymentPlan; returns the
    shared Report type (same gating as every other lint)."""
    import jax

    findings = []
    findings.extend(_rule_over_budget(plan))
    findings.extend(_rule_estimate_drift(plan))
    findings.extend(_rule_oversized_temp(plan, strict=strict))
    findings.extend(_rule_pool_misfit(plan, strict=strict))
    rules = tuple(HBM_RULES)
    if plan.comms is not None:
        # ISSUE-20: a plan that carries its interconnect component gets the
        # comms budget gate too — the deploy review reads ONE table
        from .comms import _rule_comms_over_budget

        findings.extend(_rule_comms_over_budget(
            plan.comms, subject=f"{plan.config.name}:comms"))
        rules += ("comms-over-budget",)
    al = allowlist if allowlist is not None else BUILTIN_HBM_ALLOWLIST
    try:
        backend = jax.default_backend()
    except Exception:
        backend = ""
    kept, suppressed = al.apply(findings, backend)
    return Report(name or f"hbm.residency[{plan.config.name}]", kept,
                  suppressed, rules)


# ============================================================= runtime half
def params_bytes_of(model) -> int:
    """Resident bytes of a model's parameters (the optimizer-free serving
    state): what the plan's params component and the scheduler's
    ``hbm_budget=`` sizing charge per replica (pre-tp)."""
    import jax.numpy as jnp

    total = 0
    for p in model.parameters():
        try:
            total += int(p.size) * jnp.dtype(str(p.dtype)).itemsize
        except Exception:
            total += int(getattr(getattr(p, "_value", None), "nbytes", 0))
    return total


def plan_kv_pool(budget_bytes, *, num_layers, num_kv_heads, head_dim,
                 block_size, dtype="bfloat16", slots=8, max_seq_len=None,
                 params_bytes=0, tp=1, headroom=DEFAULT_HEADROOM,
                 prefix_blocks=0, temps_bytes=0, adapter_bank_bytes=0,
                 name="planned", prefill_chunk=16, decode_steps=4, spec_k=0,
                 eos_token_id=None, decode_kernel="pallas") -> dict:
    """Size a PagedKVCache pool from an HBM budget: the runtime half the
    continuous scheduler's ``hbm_budget=`` knob consults before building
    its pool. Returns ``{"num_blocks", "fit_blocks", "target_blocks",
    "per_block_bytes", "plan"}`` where ``plan`` is the DeploymentPlan the
    scheduler publishes through the ``paddle_hbm_planned_bytes`` gauges.

    num_blocks = min(what fits the usable budget after params/tp + temps,
    what the admissible requests can reach: slots x
    blocks_for(max_seq_len) + parked prefix blocks) — the second clamp is
    what keeps a generous budget from buying unreachable blocks
    (pool-misfit's waste arm)."""
    from .compilesurface import ServingConfig

    budget_bytes = int(budget_bytes)
    usable = int(budget_bytes * (1.0 - headroom))
    fixed = (int(params_bytes) // max(1, int(tp)) + int(temps_bytes)
             + int(adapter_bank_bytes))
    sig = (int(num_layers), int(num_kv_heads), int(head_dim),
           int(block_size), 0, str(dtype))
    pbb = per_block_bytes(sig, tp=tp)
    fit = (usable - fixed) // pbb
    target = None
    if max_seq_len:
        target = (int(slots) * blocks_for(max_seq_len, block_size)
                  + int(prefix_blocks))
    num_blocks = int(min(fit, target) if target is not None else fit)
    floor = blocks_for(max_seq_len, block_size) if max_seq_len else 1
    if num_blocks < floor:
        raise ValueError(
            f"hbm budget {fmt_bytes(budget_bytes)} cannot fit a KV pool: "
            f"{fmt_bytes(max(0, usable - fixed))} left after params/temps "
            f"buys {max(0, fit)} blocks of {fmt_bytes(pbb)}, need at least "
            f"{floor}")
    config = ServingConfig(
        name=name, slots=int(slots), prefill_chunk=int(prefill_chunk),
        decode_steps=int(decode_steps), spec_k=int(spec_k),
        eos_token_id=eos_token_id, max_seq_len=max_seq_len,
        kv_signature=(int(num_layers), int(num_kv_heads), int(head_dim),
                      int(block_size), num_blocks, str(dtype)),
        decode_kernel=decode_kernel)
    plan = DeploymentPlan(
        config=config, budget_bytes=budget_bytes, headroom=headroom,
        params_bytes=int(params_bytes), tp=int(tp),
        prefix_blocks=int(prefix_blocks), temps_bytes=int(temps_bytes),
        adapter_bank_bytes=int(adapter_bank_bytes))
    return {"num_blocks": num_blocks, "fit_blocks": int(fit),
            "target_blocks": target, "per_block_bytes": pbb, "plan": plan}


# ============================================================ zoo residency
# The smoke residency the self-check/bench/tier-1 gate on: the zoo GPT's
# two default step programs against the zoo smoke pool and a 64 MiB budget
# (generous for a 2-layer smoke model — the gate is the RULES firing on
# real numbers, not a tight fit). max_seq_len=2048 makes the pool exactly
# reachable: 8 slots x blocks_for(2048) = 128 blocks = the pool.
SMOKE_BUDGET_BYTES = 64 << 20
SMOKE_MAX_SEQ_LEN = 2048


def smoke_budget_bytes() -> int:
    return SMOKE_BUDGET_BYTES


def _trace_step_program(model, kv, config, path):
    """Trace + (where the backend can) compile one continuous-scheduler
    step program at the config's geometry with fully idle inputs (the same
    write-free launches AOTWarmup uses); returns (ClosedJaxpr, measured
    memory_stats dict — empty when the backend has no real stats)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..observability.xla import memory_stats

    S, C, T = config.slots, config.prefill_chunk, config.decode_steps
    W = config.table_width
    tbl = np.zeros((S, W), np.int32)
    zeros_i = np.zeros((S,), np.int64)
    idle = np.zeros((S,), bool)
    state = model._decode_state(jnp.bfloat16)
    temps = jnp.zeros((S,), jnp.float32)
    top_ks = jnp.zeros((S,), jnp.int32)
    pools = (tuple(kv.k_pages), tuple(kv.v_pages))
    key = jax.random.key(0)
    i32 = lambda a: jnp.asarray(a, jnp.int32)  # noqa: E731
    if path == "prefill_chunk":
        ids = np.zeros((S, C), np.int64)
        model.prefill_chunk(ids, zeros_i, zeros_i, kv, tbl,
                            eos_token_id=config.eos_token_id, seed=0)
        run = model.compiled_prefill_chunk_runner(S, C)
        args = (state, jnp.asarray(ids), i32(zeros_i), i32(zeros_i),
                i32(tbl), temps, top_ks, *pools, key)
    elif path == "decode_step":
        model.decode_step(zeros_i, zeros_i, idle, kv, tbl, steps=T,
                          eos_token_id=config.eos_token_id, seed=0)
        run = model.compiled_decode_step_runner(S, T)
        args = (state, jnp.asarray(zeros_i), i32(zeros_i),
                jnp.asarray(idle), i32(zeros_i), i32(tbl),
                temps, top_ks, *pools, key)
    elif path == "verify_step":
        chunk = np.zeros((S, config.spec_k + 1), np.int64)
        model.verify_step(chunk, zeros_i, zeros_i, idle, kv, tbl, seed=0)
        run = model.compiled_verify_step_runner(S, config.spec_k + 1)
        args = (state, jnp.asarray(chunk), i32(zeros_i), i32(zeros_i),
                jnp.asarray(idle), i32(zeros_i), i32(tbl),
                temps, top_ks, *pools, key)
    else:
        raise ValueError(f"no residency trace for path {path!r}")
    closed = jax.make_jaxpr(run)(*args)
    try:
        measured = memory_stats(run.lower(*args).compile())
    except Exception:
        measured = {}
    if measured.get("estimated"):       # fallback stats are not a measurement
        measured = {}
    return closed, measured


def smoke_plan(*, budget_bytes=None, with_measured=True, config_name=None):
    """Build the zoo residency plan: smoke GPT + smoke pool + the default
    continuous paths, statically estimated and (where the backend has
    CompiledMemoryStats) measured. Shared by the zoo entry, the bench
    ``hbm_planning`` leg, and the tier-1 acceptance tests. ``config_name``
    picks one of the shipped serving configs (``--hbm NAME``); the default
    is the non-speculative shipped config."""
    import dataclasses as _dc

    from .compilesurface import default_serving_configs
    from .zoo import _gpt_smoke

    cfg_model, model = _gpt_smoke()
    model.eval()
    from ..inference.kv_cache import PagedKVCache

    shipped = default_serving_configs()
    if config_name is None:
        base = shipped[0]
    else:
        match = [c for c in shipped if c.name == config_name]
        if not match:
            raise ValueError(f"unknown serving config {config_name!r}; "
                             f"shipped: {[c.name for c in shipped]}")
        base = match[0]
    config = _dc.replace(base, name="hbm-smoke",
                         max_seq_len=SMOKE_MAX_SEQ_LEN)
    layers, kv_heads, head_dim, block_size, num_blocks, dtype = \
        config.kv_signature
    kv = PagedKVCache(layers, kv_heads, head_dim, block_size=block_size,
                      num_blocks=num_blocks, dtype=dtype)
    programs = []
    for path in config.active_paths():
        closed, measured = _trace_step_program(model, kv, config, path)
        est = estimate_peak(closed, name=path)
        real = measured.get("peak_bytes") if with_measured else None
        # a backend without donation keeps both pool copies: compare the
        # matching (undonated) walk so drift measures estimator error,
        # not the backend's donation support
        if real and not measured.get("alias_bytes"):
            est = PeakEstimate(
                est.name, est.peak_bytes_undonated,
                est.peak_bytes_undonated, est.argument_bytes,
                est.output_bytes, 0, est.temp_bytes, est.at_peak,
                est.eqn_count)
        programs.append(ProgramEstimate.from_estimate(
            est, measured=real or None))
    return DeploymentPlan(
        config=config,
        budget_bytes=int(budget_bytes or SMOKE_BUDGET_BYTES),
        params_bytes=params_bytes_of(model),
        programs=tuple(programs))


def analyze_hbm_residency(allowlist=None, *, budget_bytes=None,
                          name="hbm.residency") -> Report:
    """The ``hbm_residency`` zoo entry body: smoke plan -> the four rules.
    ``--self-check`` fails on any un-allowlisted HIGH here, which makes
    estimator drift against real backend stats a CI failure, not a shrug."""
    plan = smoke_plan(budget_bytes=budget_bytes)
    return analyze_hbm_plan(plan, allowlist=allowlist, name=name)


# ------------------------------------------------------------- fixture mode
def hbm_fixture_reports(path):
    """Seeded-violation mode for ``--hbm PATH`` (mirrors --surface): a
    ``.json`` file is a DeploymentPlan spec (``{"plan": {...}}`` or the
    plan object itself); a ``.py`` file is a PROGRAM fixture — it must
    define ``make_program()`` returning ``(fn, args)`` plus a
    ``BUDGET_BYTES`` int, and is estimated against that budget (the
    giant-broadcast-temp seed). Directories run every fixture inside.
    Everything is strict with an empty allowlist."""
    if os.path.isdir(path):
        names = sorted(n for n in os.listdir(path)
                       if n.endswith((".py", ".json")))
        out = []
        for n in names:
            out.extend(hbm_fixture_reports(os.path.join(path, n)))
        return out
    label = f"hbm[{os.path.basename(path)}]"
    if path.endswith(".json"):
        with open(path, "r") as fh:
            spec = json.load(fh)
        plan = DeploymentPlan.from_json(spec.get("plan", spec))
        return [analyze_hbm_plan(plan, strict=True, allowlist=Allowlist([]),
                                 name=label)]
    import runpy

    mod = runpy.run_path(path)
    if "make_program" not in mod or "BUDGET_BYTES" not in mod:
        raise ValueError(f"{path}: a .py hbm fixture must define "
                         "make_program() -> (fn, args) and BUDGET_BYTES")
    import jax

    from .compilesurface import ServingConfig

    fn, args = mod["make_program"]()
    closed = jax.make_jaxpr(fn)(*args)
    est = estimate_peak(closed, name=os.path.basename(path))
    budget = int(mod["BUDGET_BYTES"])
    # a program-only fixture: pool/params are zeroed out so the ONLY rules
    # with teeth are the per-program ones (oversized-temp, estimate-drift)
    config = ServingConfig(name=os.path.basename(path), slots=1,
                           max_seq_len=1,
                           kv_signature=(1, 1, 1, 1, 1, "bfloat16"))
    plan = DeploymentPlan(
        config=config, budget_bytes=budget,
        programs=(ProgramEstimate.from_estimate(est),))
    return [analyze_hbm_plan(plan, strict=True, allowlist=Allowlist([]),
                             name=label)]
