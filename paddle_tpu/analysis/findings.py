"""Typed findings + allowlist for the graph linter.

A ``Finding`` is one rule violation on one traced program: rule id, severity,
human message, source provenance (the jaxpr equation's user frame, or the
argument path for input-level findings) and a remediation hint. Severities:

* ``high`` — will burn a run: doubled HBM (missed donation), halved MXU
  throughput (f32 matmul in a bf16 block), a host round-trip inside a
  compiled hot loop, a per-step recompile. Gated: bench/tier-1/CLI
  ``--self-check`` fail on any high finding that is not allowlisted.
* ``warn`` — costs something or is fragile (weak-typed scalar captures,
  mid-sized baked constants) but does not by itself sink a run.
* ``info`` — context the reader may want; never gated.

An ``Allowlist`` suppresses findings that are INTENTIONAL, with a recorded
justification — the suppression is visible in ``Report.suppressed`` rather
than silently dropped, so "clean" always means "clean or explained". Entries
match on rule id, program-name glob, an optional message/provenance
substring, and optionally only on specific jax backends (the built-in entry
for the CPU donation skip in models/generation.py is backend-gated: donation
is unimplemented on CPU, so the paged decode program legitimately ships
undonated pools there).
"""
from __future__ import annotations

import fnmatch

__all__ = ["HIGH", "WARN", "INFO", "SEVERITIES", "Finding",
           "AllowlistEntry", "Allowlist", "BUILTIN_ALLOWLIST",
           "stale_allowlist_findings"]

HIGH = "high"
WARN = "warn"
INFO = "info"
SEVERITIES = (HIGH, WARN, INFO)


class Finding:
    """One rule violation on one analyzed program."""

    __slots__ = ("rule", "severity", "message", "where", "subject",
                 "remediation")

    def __init__(self, rule, severity, message, *, where="", subject="",
                 remediation=""):
        if severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}, "
                             f"got {severity!r}")
        self.rule = rule
        self.severity = severity
        self.message = message
        self.where = where            # "file:line (fn)" or an argument path
        self.subject = subject        # program name (set by the analyzer)
        self.remediation = remediation

    def to_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "message": self.message, "where": self.where,
                "subject": self.subject, "remediation": self.remediation}

    def __repr__(self):
        return (f"Finding({self.rule}, {self.severity}, {self.subject!r}, "
                f"{self.message!r})")

    def render(self) -> str:
        loc = f" @ {self.where}" if self.where else ""
        fix = f"\n      fix: {self.remediation}" if self.remediation else ""
        return (f"[{self.severity.upper():4s}] {self.rule}: "
                f"{self.message}{loc}{fix}")


class AllowlistEntry:
    """One justified suppression. ``subject`` is a glob over program names;
    ``contains`` (optional) must appear in the finding's message or
    provenance; ``backends`` (optional) restricts the entry to specific jax
    default backends. ``reason`` is mandatory — an allowlist entry without a
    recorded why is just a weakened rule. ``used`` records whether the entry
    suppressed anything since process start (the stale-suppression audit's
    input: a builtin entry that matched nothing across a full self-check has
    outlived its rule, or its subject glob drifted off the program names)."""

    __slots__ = ("rule", "subject", "contains", "reason", "backends", "used")

    def __init__(self, rule, subject="*", contains=None, *, reason,
                 backends=None):
        if not reason:
            raise ValueError("allowlist entries require a justification "
                             "(reason=)")
        self.rule = rule
        self.subject = subject
        self.contains = contains
        self.reason = reason
        self.backends = tuple(backends) if backends else None
        self.used = False

    def matches(self, finding: Finding, backend: str) -> bool:
        if self.rule != finding.rule:
            return False
        if self.backends is not None and backend not in self.backends:
            return False
        if not fnmatch.fnmatch(finding.subject or "", self.subject):
            return False
        if self.contains and (self.contains not in finding.message
                              and self.contains not in finding.where):
            return False
        return True

    def __repr__(self):
        return (f"AllowlistEntry({self.rule}, subject={self.subject!r}, "
                f"reason={self.reason!r})")


class Allowlist:
    def __init__(self, entries=()):
        self.entries = list(entries)

    def __iter__(self):
        return iter(self.entries)

    def extend(self, entries) -> "Allowlist":
        """A new Allowlist with `entries` appended (builtin stays intact)."""
        return Allowlist(self.entries + list(entries))

    def apply(self, findings, backend: str):
        """Split findings into (kept, suppressed) where suppressed is a list
        of (finding, entry) pairs — suppression is recorded, not silent."""
        kept, suppressed = [], []
        for f in findings:
            entry = next((e for e in self.entries if e.matches(f, backend)),
                         None)
            if entry is None:
                kept.append(f)
            else:
                entry.used = True
                suppressed.append((f, entry))
        return kept, suppressed


def stale_allowlist_findings(named_lists) -> list:
    """WARN ``allowlist-stale`` findings for entries that suppressed nothing.

    ``named_lists``: (label, Allowlist) pairs — the builtin graph / thread /
    surface / hbm lists in the self-check. Call AFTER every report has run;
    ``used`` accumulates across Allowlist.apply calls, so an entry counts as
    live if ANY program tripped it. First-match-wins means a shadowed
    duplicate also reads stale — that is a finding too (delete the shadow).
    A dead suppression is a rule silently weakened for nobody's benefit:
    either its hazard was fixed (delete the entry) or the subject glob no
    longer matches the program names (fix the glob before the hazard
    returns unsuppressed)."""
    out = []
    for label, allowlist in named_lists:
        for e in allowlist:
            if e.used:
                continue
            scope = f" [backends={','.join(e.backends)}]" if e.backends else ""
            out.append(Finding(
                "allowlist-stale", WARN,
                f"builtin {label} allowlist entry matched nothing this "
                f"self-check: rule={e.rule} subject={e.subject!r}"
                f"{scope} (reason on file: {e.reason})",
                subject=f"allowlist:{label}",
                remediation="delete the entry if its hazard was fixed, or "
                            "re-aim the subject glob at the current program "
                            "names"))
    return out


# Intentional, justified exceptions shipped with the repo. Keep this list
# SHORT — every entry is a finding the analyzer is right about but the code
# is right to keep.
BUILTIN_ALLOWLIST = Allowlist([
    # models/generation.py generate_paged: donate_argnums=(4, 5) is applied
    # only off-CPU because buffer donation is unimplemented on the CPU
    # backend (jax warns and keeps both copies anyway). On CPU the paged
    # pools therefore analyze as donation-miss; on TPU they are donated and
    # the finding disappears — which is exactly the deployment that matters.
    AllowlistEntry(
        "donation-miss", subject="*decode*paged*", contains="pages",
        backends=("cpu",),
        reason="CPU backend does not implement buffer donation "
               "(models/generation.py generate_paged donates the KV pools "
               "on accelerators only; see the donate_argnums backend gate)"),
])
