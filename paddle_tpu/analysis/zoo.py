"""The lint model zoo: the repo's own flagship programs, traced and linted.

One place builds the programs the CLI ``--self-check``, the bench
``graph_lint`` leg and the tier-1 tests all gate on:

* ``gpt_train``        — GPT smoke ``TrainStep`` (the headline workload)
* ``resnet_train``     — ResNet-18 smoke ``TrainStep`` (the vision leg)
* ``gpt_decode_dense`` — ``generate()``'s compiled prefill+scan program
* ``gpt_decode_paged`` — ``generate_paged()`` over a shared KV pool sized
  past the donation threshold, so the CPU donation skip
  (models/generation.py) is actually exercised against the allowlist
* ``gpt_prefill_chunk`` / ``gpt_decode_step`` — the continuous scheduler's
  two fixed-width step programs (inference/scheduler.py): chunked prefill
  and the slot-masked decode tick. These are the programs a token-level
  serving loop launches thousands of times per second, so host-sync and
  recompile-hazard findings here are deploy blockers; their fixed
  slot/table widths are what keeps them recompile-clean by construction.
* ``gpt_prefill_prefix`` — the SAME chunked-prefill program, launched the
  way a prefix-cache hit launches it (inference/prefix_cache.py): the live
  slot resumes at a nonzero offset past the shared prefix blocks, writing
  only into its private tail. Offsets are traced inputs, so a warm start
  must not change the program shape — this entry is the recompile-hazard
  gate for the hit path.
* ``gpt_verify_step`` — the speculative-decoding verifier
  (models/generation.py ``verify_step``): scores a fixed-width ``[S, K+1]``
  draft chunk in one forward and runs rejection sampling in-program. Same
  deploy-blocker standard as the decode tick — the acceptance pattern must
  never leak into the program shape.
* ``gpt_prefill_chunk_tp`` / ``gpt_decode_step_tp`` / ``gpt_verify_step_tp``
  — the SAME three step programs traced under the ``("dp","tp")`` serving
  mesh (distributed/mesh.py ``serving_mesh``): tp shards the qkv/ffn/
  embedding weights and the paged pool's head axis, and the split-KV kernel
  runs inside a shard_map over tp. These entries declare the deployment
  axes, so the collective-axis rule is their deploy gate: a collective
  bound to any axis the serving mesh doesn't carry is a HIGH finding.
* ``compile_surface`` — the ISSUE-13 program-inventory contract
  (analysis/compilesurface.py) over the decode paths above: the derived
  cache-key set of the shipped serving configs must be closed and covered
  by the default manifest, and every key-site path must map to a zoo
  family in this registry (zoo_cross_check).
* ``comms_surface`` — the ISSUE-20 sharding-and-collective contract
  (analysis/comms.py): compile the three continuous step programs under
  the tp=2 serving mesh, inventory every collective GSPMD inserted into
  the optimized HLO, check the compiled parameter/output shardings
  against ``SpecLayout.step_contract()``, and size per-tick wire bytes
  against the chip's ICI. A HIGH here means a mid-program reshard
  appeared (or an old one changed shape), the layout contract rotted, or
  the decode tick no longer fits on the wire.
* ``hbm_residency`` — the ISSUE-14 memory contract (analysis/hbm.py): the
  default continuous ServingConfig's per-chip residency (params + smoke
  KV pool + static temp peaks of its step programs) against the smoke HBM
  budget, with the static estimator drift-checked against the backend's
  real ``CompiledMemoryStats`` wherever those exist. A HIGH here means
  the shipped defaults no longer fit their declared chip — or the
  estimator went blind.

Smoke sizes on purpose: lint findings are properties of the GRAPH, not the
weights, and the same rules fire on a 2-layer 64-wide GPT as on 350M — so
the gate stays cheap enough for tier-1.
"""
from __future__ import annotations

import numpy as np

from .core import Thresholds, analyze, analyze_train_step

__all__ = ["ZOO_PROGRAMS", "zoo_report", "zoo_reports"]


def _gpt_smoke():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                    num_heads=4, max_position=128)
    return cfg, GPTForCausalLM(cfg)


def gpt_train_report(thresholds=None, allowlist=None):
    import paddle_tpu as paddle
    from paddle_tpu.jit.train import TrainStep

    cfg, model = _gpt_smoke()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = TrainStep(model, lambda logits, loss: loss, opt)
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 16))
    x = paddle.to_tensor(ids.astype("int64"))
    y = paddle.to_tensor(np.roll(ids, -1, axis=1).astype("int64"))
    return analyze_train_step(step, x, labels=y, name="train_step:GPT",
                              thresholds=thresholds, allowlist=allowlist)


def resnet_train_report(thresholds=None, allowlist=None):
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.jit.train import TrainStep

    paddle.seed(0)
    model = paddle.vision.models.resnet18(num_classes=10)
    loss_fn = nn.CrossEntropyLoss()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    step = TrainStep(model, lambda out, y: loss_fn(out, y), opt)
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(2, 3, 32, 32).astype("float32"))
    y = paddle.to_tensor(rs.randint(0, 10, 2).astype("int64"))
    return analyze_train_step(step, x, y, name="train_step:ResNet18",
                              thresholds=thresholds, allowlist=allowlist)


def gpt_decode_dense_report(thresholds=None, allowlist=None):
    import jax

    import paddle_tpu as paddle

    cfg, model = _gpt_smoke()
    model.eval()
    B, P, NEW = 2, 8, 4
    ids = paddle.to_tensor(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (B, P)).astype("int64"))
    model.generate(ids, max_new_tokens=NEW)  # builds + caches the runner
    run = model.compiled_generate_runner(B, P, NEW)
    import jax.numpy as jnp

    state = model._decode_state(jnp.bfloat16)
    # sampler params are TRACED [B] inputs since the fused-sampler refactor
    # (ISSUE-10 satellite): one program serves greedy AND sampled configs,
    # and sampling lives inside the scan body — this entry is what keeps
    # the dense decode program host-sync-clean with no allowlist entries.
    # Nonzero temps/top_ks here lint the SAMPLED branch of the fused math.
    return analyze(run, state, ids._value,
                   jnp.full((B,), 0.8, jnp.float32),
                   jnp.full((B,), 4, jnp.int32), jax.random.key(0),
                   _name="gpt.decode.dense",
                   _arg_labels=("state", "prompt", "temperatures", "top_ks",
                                "rng_key"),
                   _thresholds=thresholds, _allowlist=allowlist)


def gpt_decode_paged_report(thresholds=None, allowlist=None):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.inference.kv_cache import PagedKVCache

    cfg, model = _gpt_smoke()
    model.eval()
    B, P, NEW = 2, 8, 4
    # pool sized past the donation threshold (1 MiB/pool) so the
    # donation-miss rule actually judges it: on CPU the pools analyze as
    # non-donated (generation.py's backend gate) and the builtin allowlist
    # must carry the finding; on TPU they are donated and it vanishes.
    kv = PagedKVCache(cfg.num_layers, cfg.num_kv_heads,
                      cfg.hidden_size // cfg.num_heads,
                      block_size=128, num_blocks=128, dtype="bfloat16")
    plens = np.full((B,), P, np.int64)
    for i in range(B):
        kv.reserve(i, P + NEW)
    nb = kv.blocks_for(P + NEW)
    tbl = np.stack([kv.block_table(i, pad_to=nb) for i in range(B)])
    ids = paddle.to_tensor(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (B, P)).astype("int64"))
    model.generate_paged(ids, plens, kv, tbl, max_new_tokens=NEW)
    run = model.compiled_generate_paged_runner(B, P, NEW)
    return analyze(
        run, model._decode_state(jnp.bfloat16), ids._value,
        jnp.asarray(plens, jnp.int32), jnp.asarray(tbl, jnp.int32),
        tuple(kv.k_pages), tuple(kv.v_pages), jax.random.key(0),
        _name="gpt.decode.paged",
        _arg_labels=("state", "prompt", "prompt_lens", "tables",
                     "k_pages", "v_pages", "rng_key"),
        _thresholds=thresholds, _allowlist=allowlist)


def _continuous_smoke():
    """Shared builder for the two continuous-scheduler step programs: a
    smoke GPT plus a pool sized past the donation threshold (like the paged
    zoo entry, so the CPU donation allowlist path stays exercised), with one
    slot live and one idle — the masked-slot configuration the scheduler
    actually runs."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.inference.kv_cache import PagedKVCache

    cfg, model = _gpt_smoke()
    model.eval()
    S, C, NEW, T = 2, 8, 4, 2
    kv = PagedKVCache(cfg.num_layers, cfg.num_kv_heads,
                      cfg.hidden_size // cfg.num_heads,
                      block_size=128, num_blocks=128, dtype="bfloat16")
    kv.reserve("seq", C + NEW)
    nb = kv.blocks_for(C + NEW)
    tbl = np.zeros((S, nb), np.int32)
    tbl[0] = kv.block_table("seq", pad_to=nb)
    ids = np.zeros((S, C), np.int64)
    ids[0] = np.random.RandomState(0).randint(0, cfg.vocab_size, C)
    return model, kv, tbl, ids, S, C, NEW, T, jnp


def _under_serving_mesh(report_fn, thresholds, allowlist):
    """Run a step-program report under the ("dp","tp") serving mesh.

    tp=2 when the process has the devices (tier-1 sets
    xla_force_host_platform_device_count=8; a real TPU slice always
    qualifies), else tp=1 — the entry still lints with the deployment axes
    declared, just without the sharding. The previous global mesh is
    restored afterwards so entry order never leaks mesh state."""
    import jax

    from paddle_tpu.distributed.mesh import get_mesh, serving_mesh, set_mesh

    prev = get_mesh()
    tp = 2 if len(jax.devices()) >= 2 else 1
    serving_mesh(dp=1, tp=tp)
    try:
        return report_fn(thresholds=thresholds, allowlist=allowlist,
                         _tp=True)
    finally:
        set_mesh(prev)


def gpt_prefill_chunk_report(thresholds=None, allowlist=None, _tp=False):
    import jax

    from .core import analyze

    model, kv, tbl, ids, S, C, NEW, T, jnp = _continuous_smoke()
    offs = np.zeros(S, np.int64)
    lens = np.asarray([C, 0], np.int64)          # slot 1 idle (masked)
    model.prefill_chunk(ids, offs, lens, kv, tbl)   # builds + caches runner
    run = model.compiled_prefill_chunk_runner(S, C)
    return analyze(
        run, model._decode_state(jnp.bfloat16), jnp.asarray(ids),
        jnp.asarray(offs, jnp.int32), jnp.asarray(lens, jnp.int32),
        jnp.asarray(tbl, jnp.int32),
        # sampling params are TRACED per-slot inputs (PR 8): mixed-sampler
        # traffic shares this one program, so they lint as arguments
        jnp.zeros((S,), jnp.float32), jnp.zeros((S,), jnp.int32),
        tuple(kv.k_pages), tuple(kv.v_pages),
        jax.random.key(0),
        _name="gpt.decode.paged_prefill_chunk" + ("_tp" if _tp else ""),
        _mesh_axes=("dp", "tp") if _tp else None,
        _arg_labels=("state", "chunk", "offsets", "chunk_lens", "tables",
                     "temperatures", "top_ks", "k_pages", "v_pages",
                     "rng_key"),
        _thresholds=thresholds, _allowlist=allowlist)


def gpt_prefill_chunk_tp_report(thresholds=None, allowlist=None):
    """Chunked prefill traced under the ("dp","tp") serving mesh: tp shards
    the qkv/ffn/embedding weights and the paged pool's head axis. The
    collective-axis rule is the deploy gate — every collective GSPMD or the
    split-KV shard_map inserts must answer to a declared deployment axis."""
    return _under_serving_mesh(gpt_prefill_chunk_report, thresholds,
                               allowlist)


def gpt_decode_step_report(thresholds=None, allowlist=None, _tp=False):
    import jax

    from .core import analyze

    model, kv, tbl, ids, S, C, NEW, T, jnp = _continuous_smoke()
    # prefill the live slot so the step program runs against real state
    model.prefill_chunk(ids, np.zeros(S, np.int64),
                        np.asarray([C, 0], np.int64), kv, tbl)
    tok = np.zeros(S, np.int64)
    lens = np.asarray([C, 0], np.int64)
    act = np.asarray([True, False])
    lmax = np.asarray([C + NEW, 0], np.int64)
    model.decode_step(tok, lens, act, kv, tbl, steps=T, max_lens=lmax)
    run = model.compiled_decode_step_runner(S, T)
    return analyze(
        run, model._decode_state(jnp.bfloat16), jnp.asarray(tok),
        jnp.asarray(lens, jnp.int32), jnp.asarray(act),
        jnp.asarray(lmax, jnp.int32), jnp.asarray(tbl, jnp.int32),
        jnp.zeros((S,), jnp.float32), jnp.zeros((S,), jnp.int32),
        tuple(kv.k_pages), tuple(kv.v_pages), jax.random.key(0),
        _name="gpt.decode.paged_step" + ("_tp" if _tp else ""),
        _mesh_axes=("dp", "tp") if _tp else None,
        _arg_labels=("state", "tokens", "lengths", "active", "max_lens",
                     "tables", "temperatures", "top_ks", "k_pages",
                     "v_pages", "rng_key"),
        _thresholds=thresholds, _allowlist=allowlist)


def gpt_decode_step_tp_report(thresholds=None, allowlist=None):
    """The decode tick under the ("dp","tp") serving mesh — the program a
    tensor-parallel serving replica launches per token. The split-KV kernel
    runs head-local inside a shard_map over tp (no collective inside; the
    only cross-chip exchange is the sampled-logit gather GSPMD inserts after
    the vocab-sharded lm_head), so the collective-axis gate here is what
    stops an mp-named training program from reaching a tp-named mesh."""
    return _under_serving_mesh(gpt_decode_step_report, thresholds, allowlist)


def gpt_prefill_prefix_report(thresholds=None, allowlist=None):
    """Chunked prefill entered through a prefix-cache hit.

    A donor request commits a 16-token prefix, registers it and releases
    (parking two full blocks in the evictable tier); a second request with
    the same prefix plus an 8-token novel suffix reserves THROUGH the
    shared pairs and prefills only the suffix at offset 16. The analyzed
    program is byte-for-byte the cold prefill program — same runner cache
    key — which is the point: a hit changes only the (traced) offsets, so
    it can never trigger a recompile or write into shared blocks."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.inference.kv_cache import PagedKVCache
    from paddle_tpu.inference.prefix_cache import PrefixCache

    from .core import analyze

    cfg, model = _gpt_smoke()
    model.eval()
    S, BS, PFX, C, NEW = 2, 8, 16, 8, 4
    # block_size=8 so a 16-token prefix is exactly two FULL shareable
    # blocks; 1024 blocks keeps each pool at the 1 MiB donation threshold
    # (4 kv heads x 16 head_dim x bf16) so the CPU donation allowlist path
    # stays exercised, same as the other paged entries.
    kv = PagedKVCache(cfg.num_layers, cfg.num_kv_heads,
                      cfg.hidden_size // cfg.num_heads,
                      block_size=BS, num_blocks=1024, dtype="bfloat16")
    px = PrefixCache(kv)
    rs = np.random.RandomState(0)
    prefix = rs.randint(0, cfg.vocab_size, PFX).astype(np.int64)
    suffix = rs.randint(0, cfg.vocab_size, C).astype(np.int64)
    # donor: commit the prefix, index it, release -> two parked blocks
    kv.reserve("donor", PFX)
    kv.append_tokens("donor", PFX)
    px.register("donor", prefix)
    kv.release("donor")
    # hit: reserve through the shared pairs; committed length lands at 16
    hit = px.lookup(np.concatenate([prefix, suffix]))
    kv.reserve("hit", PFX + C + NEW, shared=hit.pairs)
    assert kv.length("hit") == PFX, "zoo prefix hit did not attach"
    nb = kv.blocks_for(PFX + C + NEW)
    tbl = np.zeros((S, nb), np.int32)
    tbl[0] = kv.block_table("hit", pad_to=nb)
    ids = np.zeros((S, C), np.int64)
    ids[0] = suffix
    offs = np.asarray([PFX, 0], np.int64)   # resume PAST the shared prefix
    lens = np.asarray([C, 0], np.int64)     # slot 1 idle (masked)
    model.prefill_chunk(ids, offs, lens, kv, tbl)   # builds + caches runner
    run = model.compiled_prefill_chunk_runner(S, C)
    return analyze(
        run, model._decode_state(jnp.bfloat16), jnp.asarray(ids),
        jnp.asarray(offs, jnp.int32), jnp.asarray(lens, jnp.int32),
        jnp.asarray(tbl, jnp.int32),
        jnp.zeros((S,), jnp.float32), jnp.zeros((S,), jnp.int32),
        tuple(kv.k_pages), tuple(kv.v_pages),
        jax.random.key(0),
        _name="gpt.decode.paged_prefill_prefix",
        _arg_labels=("state", "chunk", "offsets", "chunk_lens", "tables",
                     "temperatures", "top_ks", "k_pages", "v_pages",
                     "rng_key"),
        _thresholds=thresholds, _allowlist=allowlist)


def gpt_verify_step_report(thresholds=None, allowlist=None, _tp=False):
    import jax

    from .core import analyze

    model, kv, tbl, ids, S, C, NEW, T, jnp = _continuous_smoke()
    # prefill the live slot so verification runs against committed state
    model.prefill_chunk(ids, np.zeros(S, np.int64),
                        np.asarray([C, 0], np.int64), kv, tbl)
    K = 3
    chunk = np.zeros((S, K + 1), np.int64)
    chunk[0] = np.random.RandomState(1).randint(0, 512, K + 1)
    offs = np.asarray([C, 0], np.int64)
    dlens = np.asarray([K, 0], np.int64)
    act = np.asarray([True, False])
    lmax = np.asarray([C + NEW, 0], np.int64)
    model.verify_step(chunk, offs, dlens, act, kv, tbl, max_lens=lmax)
    run = model.compiled_verify_step_runner(S, K + 1)
    return analyze(
        run, model._decode_state(jnp.bfloat16), jnp.asarray(chunk),
        jnp.asarray(offs, jnp.int32), jnp.asarray(dlens, jnp.int32),
        jnp.asarray(act), jnp.asarray(lmax, jnp.int32),
        jnp.asarray(tbl, jnp.int32),
        jnp.zeros((S,), jnp.float32), jnp.zeros((S,), jnp.int32),
        tuple(kv.k_pages), tuple(kv.v_pages), jax.random.key(0),
        _name="gpt.decode.paged_verify_step" + ("_tp" if _tp else ""),
        _mesh_axes=("dp", "tp") if _tp else None,
        _arg_labels=("state", "chunk", "offsets", "draft_lens", "active",
                     "max_lens", "tables", "temperatures", "top_ks",
                     "k_pages", "v_pages", "rng_key"),
        _thresholds=thresholds, _allowlist=allowlist)


def gpt_verify_step_tp_report(thresholds=None, allowlist=None):
    """The speculative verifier under the ("dp","tp") serving mesh — same
    deploy-blocker standard as the sharded decode tick; rejection sampling
    runs on the gathered logits so acceptance never crosses chips."""
    return _under_serving_mesh(gpt_verify_step_report, thresholds,
                               allowlist)


def _continuous_lora_smoke():
    """The continuous smoke pool wrapped by an AdapterRegistry (ISSUE-15):
    4 adapter rows + the identity slot, rank 8, over the smoke GPT's 4
    target projections — exactly the ("lora", 5, 8, 4) signature the
    continuous-lora ServingConfig declares. One real rank-4 adapter is
    registered and routed to the live slot so the banked gather lints with
    a non-identity row in flight."""
    model, kv, tbl, ids, S, C, NEW, T, jnp = _continuous_smoke()
    from paddle_tpu.inference.adapters import AdapterRegistry

    reg = AdapterRegistry(model, max_adapters=4, max_rank=8)
    rs = np.random.RandomState(7)
    weights = {}
    for path in reg.target_paths():
        d_in, d_out = reg.dims(path)
        weights[path] = (rs.randn(d_in, 4).astype(np.float32) * 0.02,
                         rs.randn(4, d_out).astype(np.float32) * 0.02)
    row = reg.register("zoo-adapter", weights, alpha=8.0)
    aidx = np.zeros(S, np.int32)
    aidx[0] = row                   # live slot adapted, idle slot identity
    return model, kv, tbl, ids, S, C, NEW, T, jnp, reg, aidx


def gpt_prefill_chunk_lora_report(thresholds=None, allowlist=None):
    """Chunked prefill with the banked LoRA gather traced in (ISSUE-15).

    The adapter index and the parameter bank are ARGUMENTS of the step
    program — like the PR-8 sampler knobs, any adapter mix, load, or
    unload reuses this one program; only the bank SHAPE is in the cache
    key. The lint proves the gathered delta path introduces no new
    donation or layout hazards over the base entry."""
    import jax

    from .core import analyze

    (model, kv, tbl, ids, S, C, NEW, T, jnp,
     reg, aidx) = _continuous_lora_smoke()
    offs = np.zeros(S, np.int64)
    lens = np.asarray([C, 0], np.int64)          # slot 1 idle (masked)
    model.prefill_chunk(ids, offs, lens, kv, tbl,
                        adapters=reg, adapter_slots=aidx)
    run = model.compiled_prefill_chunk_runner(
        S, C, adapter_signature=reg.signature())
    return analyze(
        run, model._decode_state(jnp.bfloat16), jnp.asarray(ids),
        jnp.asarray(offs, jnp.int32), jnp.asarray(lens, jnp.int32),
        jnp.asarray(tbl, jnp.int32),
        jnp.zeros((S,), jnp.float32), jnp.zeros((S,), jnp.int32),
        tuple(kv.k_pages), tuple(kv.v_pages),
        jnp.asarray(aidx, jnp.int32), reg.bank(),
        jax.random.key(0),
        _name="gpt.decode.paged_prefill_chunk_lora",
        _arg_labels=("state", "chunk", "offsets", "chunk_lens", "tables",
                     "temperatures", "top_ks", "k_pages", "v_pages",
                     "adapter_slots", "bank", "rng_key"),
        _thresholds=thresholds, _allowlist=allowlist)


def gpt_decode_step_lora_report(thresholds=None, allowlist=None):
    """The decode tick with the banked LoRA gather traced in — the program
    every heterogeneous-adapter batch launches per token (ISSUE-15)."""
    import jax

    from .core import analyze

    (model, kv, tbl, ids, S, C, NEW, T, jnp,
     reg, aidx) = _continuous_lora_smoke()
    model.prefill_chunk(ids, np.zeros(S, np.int64),
                        np.asarray([C, 0], np.int64), kv, tbl,
                        adapters=reg, adapter_slots=aidx)
    tok = np.zeros(S, np.int64)
    lens = np.asarray([C, 0], np.int64)
    act = np.asarray([True, False])
    lmax = np.asarray([C + NEW, 0], np.int64)
    model.decode_step(tok, lens, act, kv, tbl, steps=T, max_lens=lmax,
                      adapters=reg, adapter_slots=aidx)
    run = model.compiled_decode_step_runner(
        S, T, adapter_signature=reg.signature())
    return analyze(
        run, model._decode_state(jnp.bfloat16), jnp.asarray(tok),
        jnp.asarray(lens, jnp.int32), jnp.asarray(act),
        jnp.asarray(lmax, jnp.int32), jnp.asarray(tbl, jnp.int32),
        jnp.zeros((S,), jnp.float32), jnp.zeros((S,), jnp.int32),
        tuple(kv.k_pages), tuple(kv.v_pages),
        jnp.asarray(aidx, jnp.int32), reg.bank(), jax.random.key(0),
        _name="gpt.decode.paged_step_lora",
        _arg_labels=("state", "tokens", "lengths", "active", "max_lens",
                     "tables", "temperatures", "top_ks", "k_pages",
                     "v_pages", "adapter_slots", "bank", "rng_key"),
        _thresholds=thresholds, _allowlist=allowlist)


def gpt_verify_step_lora_report(thresholds=None, allowlist=None):
    """The speculative verifier with the banked LoRA gather traced in —
    draft acceptance under an adapted target model (ISSUE-15)."""
    import jax

    from .core import analyze

    (model, kv, tbl, ids, S, C, NEW, T, jnp,
     reg, aidx) = _continuous_lora_smoke()
    model.prefill_chunk(ids, np.zeros(S, np.int64),
                        np.asarray([C, 0], np.int64), kv, tbl,
                        adapters=reg, adapter_slots=aidx)
    K = 3
    chunk = np.zeros((S, K + 1), np.int64)
    chunk[0] = np.random.RandomState(1).randint(0, 512, K + 1)
    offs = np.asarray([C, 0], np.int64)
    dlens = np.asarray([K, 0], np.int64)
    act = np.asarray([True, False])
    lmax = np.asarray([C + NEW, 0], np.int64)
    model.verify_step(chunk, offs, dlens, act, kv, tbl, max_lens=lmax,
                      adapters=reg, adapter_slots=aidx)
    run = model.compiled_verify_step_runner(
        S, K + 1, adapter_signature=reg.signature())
    return analyze(
        run, model._decode_state(jnp.bfloat16), jnp.asarray(chunk),
        jnp.asarray(offs, jnp.int32), jnp.asarray(dlens, jnp.int32),
        jnp.asarray(act), jnp.asarray(lmax, jnp.int32),
        jnp.asarray(tbl, jnp.int32),
        jnp.zeros((S,), jnp.float32), jnp.zeros((S,), jnp.int32),
        tuple(kv.k_pages), tuple(kv.v_pages),
        jnp.asarray(aidx, jnp.int32), reg.bank(), jax.random.key(0),
        _name="gpt.decode.paged_verify_step_lora",
        _arg_labels=("state", "chunk", "offsets", "draft_lens", "active",
                     "max_lens", "tables", "temperatures", "top_ks",
                     "k_pages", "v_pages", "adapter_slots", "bank",
                     "rng_key"),
        _thresholds=thresholds, _allowlist=allowlist)


def compile_surface_report(thresholds=None, allowlist=None):
    """The compile-surface contract (ISSUE-13): not a traced program but
    the inventory OVER the decode programs above — AST-extract every
    ``_runner_for`` cache-key schema from models/generation.py, derive the
    closed program set of the shipped serving configs, and lint it against
    the default manifest (unbounded-key / manifest-incomplete /
    dead-bucket). Also cross-checks ZOO_FAMILIES against this registry: a
    new decode path without a zoo lint family fails the self-check HERE,
    not silently. Graph-lint ``thresholds`` do not apply to host-side AST
    analysis; the parameter exists for registry uniformity."""
    del thresholds
    from .compilesurface import analyze_compile_surface, zoo_cross_check

    zoo_cross_check()
    return analyze_compile_surface(allowlist=allowlist,
                                   name="compile.surface")


def hbm_residency_report(thresholds=None, allowlist=None):
    """The HBM residency contract (ISSUE-14): statically estimate the peak
    memory of the default continuous config's step programs, compose the
    per-chip plan (params + pool + temps) against the smoke budget, and run
    the four residency rules — drift-gated against real compiled
    memory_stats where the backend provides them. Graph-lint ``thresholds``
    do not apply; the parameter exists for registry uniformity."""
    del thresholds
    from .hbm import analyze_hbm_residency

    return analyze_hbm_residency(allowlist=allowlist, name="hbm.residency")


def comms_surface_report(thresholds=None, allowlist=None):
    """The sharding-and-collective contract (ISSUE-20): compile the three
    continuous step programs under the serving mesh (tp=2 where the host
    exposes >=2 devices), parse the post-SPMD optimized HLO for every
    collective GSPMD inserted, and run the five comms rules — implicit
    reshards, layout-contract drift, replicated large buffers, dead mesh
    axes, and the per-tick interconnect budget. Graph-lint ``thresholds``
    do not apply to HLO-text analysis; the parameter exists for registry
    uniformity."""
    del thresholds
    from .comms import analyze_step_comms

    return analyze_step_comms(allowlist=allowlist, name="comms.surface")


ZOO_PROGRAMS = {
    "gpt_train": gpt_train_report,
    "resnet_train": resnet_train_report,
    "gpt_decode_dense": gpt_decode_dense_report,
    "gpt_decode_paged": gpt_decode_paged_report,
    "gpt_prefill_chunk": gpt_prefill_chunk_report,
    "gpt_prefill_prefix": gpt_prefill_prefix_report,
    "gpt_decode_step": gpt_decode_step_report,
    "gpt_verify_step": gpt_verify_step_report,
    "gpt_prefill_chunk_tp": gpt_prefill_chunk_tp_report,
    "gpt_decode_step_tp": gpt_decode_step_tp_report,
    "gpt_verify_step_tp": gpt_verify_step_tp_report,
    "gpt_prefill_chunk_lora": gpt_prefill_chunk_lora_report,
    "gpt_decode_step_lora": gpt_decode_step_lora_report,
    "gpt_verify_step_lora": gpt_verify_step_lora_report,
    "compile_surface": compile_surface_report,
    "hbm_residency": hbm_residency_report,
    "comms_surface": comms_surface_report,
}


def zoo_report(name, thresholds=None, allowlist=None):
    return ZOO_PROGRAMS[name](thresholds=thresholds, allowlist=allowlist)


def zoo_reports(include=None, thresholds=None, allowlist=None):
    """Lint the bundled programs; returns a list of Reports. ``include``
    restricts to a subset of ``ZOO_PROGRAMS`` keys."""
    names = list(ZOO_PROGRAMS) if include is None else list(include)
    th = thresholds or Thresholds()
    return [zoo_report(n, thresholds=th, allowlist=allowlist)
            for n in names]
