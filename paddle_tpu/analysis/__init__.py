"""paddle_tpu.analysis — graph lint: static analysis over traced programs.

Because every training step and decode loop in this framework is ONE traced
program (ClosedJaxpr → StableHLO), the whole program is inspectable BEFORE
it runs. This package is the missing correctness-tooling leg next to
observability: where the PR 4 recompilation sentinel fires after a recompile
has already cost a step, the linter flags the hazard at trace time.

    from paddle_tpu import analysis
    report = analysis.analyze(jitted_fn, *example_args)
    report = analysis.analyze_train_step(step, x, labels=y)
    for f in report.high():
        print(f.render())

Rules: donation-miss, dtype-upcast, host-sync, constant-bloat,
recompile-hazard, collective-axis (catalog: docs/ANALYSIS.md). Gating:
``python -m paddle_tpu.analysis --self-check`` (CLI over the bundled model
zoo), the bench ``graph_lint`` leg, and ``StepMonitor(lint=True)`` which
lints once at first compile and counts findings in
``paddle_analysis_findings_total{rule,severity}``.

The package's second leg is the THREAD lint (``analysis/threads.py``): the
same Finding/Allowlist/Report machinery run as an AST pass over the host
runtime itself — lock-order cycles, unguarded shared writes, blocking calls
under locks — plus the runtime lock witness (``analysis/lockwitness.py``)
the chaos suite activates to check the observed acquisition order against
the static graph. ``--self-check`` gates both.

The third leg is the COMPILE-SURFACE lint (``analysis/compilesurface.py``,
ISSUE-13): AST-extract the ``cache_key`` schema at every ``_runner_for``
site in models/generation.py, derive the closed program inventory of a
``ServingConfig``, and check it against a declared ``ProgramManifest``
(rules: manifest-incomplete, unbounded-key, dead-bucket). The runtime twin
is ``inference/warmup.py`` — AOT warmup of exactly that manifest gating
/readyz, plus the post-ready recompile sentinel the chaos suite arms.

The fourth leg is the HBM RESIDENCY lint (``analysis/hbm.py``, ISSUE-14):
a jaxpr-level liveness walk estimating each program's peak-memory
watermark (drift-checked against the backend's real CompiledMemoryStats),
composed into a per-chip ``DeploymentPlan`` — params/tp + KV pool + prefix
tier + temps against a declared HBM budget (rules: hbm-over-budget,
estimate-drift, oversized-temp, pool-misfit). The runtime twin is
``plan_kv_pool`` — the continuous scheduler's ``hbm_budget=`` knob sizes
its pool from the plan and publishes ``paddle_hbm_planned_bytes``.

The fifth leg is the SHARDING & COLLECTIVE lint (``analysis/comms.py``,
ISSUE-20): compile the continuous step programs under the tp serving
mesh, inventory every collective GSPMD inserted into the optimized HLO
(kind, shape, replica groups, bytes-on-wire), and check the compiled
parameter/output shardings against ``SpecLayout.step_contract()`` (rules:
implicit-reshard, layout-contract-drift, replicated-large-buffer,
dead-mesh-axis, comms-over-budget — the last sized against the chip's
ICI from ``observability.xla.ICI_BANDWIDTH_BYTES``). The runtime twin is
``DeploymentPlan.comms`` — the deploy review reads wire-bytes-per-tick
next to residency in one table. ``--self-check`` gates all five.
"""
from .core import (  # noqa: F401
    Program,
    Report,
    Thresholds,
    analyze,
    analyze_jaxpr,
    analyze_lowered,
    analyze_train_step,
)
from .findings import (  # noqa: F401
    BUILTIN_ALLOWLIST,
    HIGH,
    INFO,
    WARN,
    Allowlist,
    AllowlistEntry,
    Finding,
    stale_allowlist_findings,
)
from .hbm import (  # noqa: F401
    BUILTIN_HBM_ALLOWLIST,
    HBM_RULES,
    DeploymentPlan,
    PeakEstimate,
    ProgramEstimate,
    analyze_hbm_plan,
    analyze_hbm_residency,
    estimate_memory_stats,
    estimate_peak,
    hbm_fixture_reports,
    params_bytes_of,
    plan_kv_pool,
)
from .comms import (  # noqa: F401
    BUILTIN_COMMS_ALLOWLIST,
    COMMS_RULES,
    CollectiveOp,
    CommsBudget,
    CommsEstimate,
    analyze_comms_surfaces,
    analyze_step_comms,
    bytes_on_wire,
    collective_inventory,
    comms_fixture_reports,
    compiled_comms_surface,
    render_comms_table,
    sampled_logits_gather_surface,
    smoke_comms_budget,
    step_comms_surfaces,
)
from .lockwitness import (  # noqa: F401
    LockWitness,
    make_lock,
    make_rlock,
)
from .compilesurface import (  # noqa: F401
    BUILTIN_SURFACE_ALLOWLIST,
    SURFACE_RULES,
    CompileSurfaceError,
    ProgramManifest,
    ServingConfig,
    analyze_compile_surface,
    default_manifest,
    default_serving_configs,
    extract_key_schemas,
    surface_fixture_reports,
    zoo_cross_check,
)
from .rules import RULES  # noqa: F401
from .threads import (  # noqa: F401
    BUILTIN_THREAD_ALLOWLIST,
    RUNTIME_MODULES,
    THREAD_RULES,
    analyze_threads,
    lock_order_graph,
)
