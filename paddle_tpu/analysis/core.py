"""Graph lint core: trace a program, hand its jaxpr to the rules, report.

The framework's thesis makes this possible: every training step and decode
loop is ONE traced program (ClosedJaxpr -> StableHLO), so hazards that only
surface as a melted dashboard at runtime — a forgotten donation doubling
HBM, an f32 matmul inside a bf16 block, a host callback inside the decode
scan — are statically visible before anything executes. This module owns the
program model and the walk; the rules live in ``rules.py``; severities,
findings and the allowlist in ``findings.py``.

Entry points (all return a ``Report``):

* ``analyze(fn, *args, **kwargs)`` — trace ``fn`` abstractly
  (``jax.make_jaxpr``; no device execution) and lint the jaxpr. Donation
  flags are read off the pjit equation when ``fn`` is jitted.
* ``analyze_jaxpr(closed_jaxpr, ...)`` — lint an already-traced program.
* ``analyze_lowered(lowered, ...)`` — lint a ``jax.stages.Lowered``: donation
  from ``args_info`` + the StableHLO text rules (reduced rule set; the
  jaxpr-level rules need ``analyze``/``analyze_jaxpr``).
* ``analyze_train_step(step, *args, **kwargs)`` — lint a
  ``jit/train.py:TrainStep`` exactly as its next ``__call__`` would trace,
  without mutating optimizer bookkeeping.

Nothing here executes the analyzed program and nothing raises out of the
rule loop: a rule that crashes on an exotic jaxpr becomes an ``info``
finding (rule-error), never an exception in the caller's training loop.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .findings import BUILTIN_ALLOWLIST, HIGH, INFO, WARN, Finding

__all__ = ["Thresholds", "InputInfo", "Program", "Report", "analyze",
           "analyze_jaxpr", "analyze_lowered", "analyze_train_step",
           "iter_eqns", "iter_consts", "source_of"]


class Thresholds:
    """Byte/count knobs the rules read. Defaults target real models; tests
    and the CLI can tighten them to exercise rules on smoke programs."""

    def __init__(self, donation_min_bytes=1 << 20, const_high_bytes=1 << 20,
                 const_warn_bytes=128 << 10, max_findings_per_rule=16):
        self.donation_min_bytes = int(donation_min_bytes)
        self.const_high_bytes = int(const_high_bytes)
        self.const_warn_bytes = int(const_warn_bytes)
        self.max_findings_per_rule = int(max_findings_per_rule)


class InputInfo:
    """One flattened program input: tree path, aval, donation flag
    (None = unknown: the program was not jitted and no donate_argnums were
    declared, so donation cannot be judged)."""

    __slots__ = ("path", "aval", "donated")

    def __init__(self, path, aval, donated):
        self.path = path
        self.aval = aval
        self.donated = donated

    @property
    def nbytes(self) -> int:
        return aval_bytes(self.aval)


def aval_bytes(aval) -> int:
    try:
        size = int(math.prod(aval.shape)) if aval.shape else 1
        return size * jnp.dtype(aval.dtype).itemsize
    except Exception:
        return 0


def fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024.0
    return f"{n} B"


class Program:
    """Everything a rule may inspect about one traced program."""

    def __init__(self, name, closed_jaxpr, inputs, *, mesh_axes=None,
                 hot=True, static_args=None, compiled=None,
                 thresholds=None):
        self.name = name
        self.closed_jaxpr = closed_jaxpr
        self.inputs = inputs                    # list[InputInfo]
        self.mesh_axes = (tuple(mesh_axes) if mesh_axes is not None else None)
        self.hot = bool(hot)
        self.static_args = static_args or {}    # label -> value
        self.compiled = compiled                # optional jax executable
        self.thresholds = thresholds or Thresholds()

    @property
    def jaxpr(self):
        return self.closed_jaxpr.jaxpr


class Report:
    """The outcome of linting one program: kept findings, suppressed
    (finding, allowlist-entry) pairs, and the rules that ran."""

    def __init__(self, name, findings, suppressed, rules_run):
        self.name = name
        self.findings = list(findings)
        self.suppressed = list(suppressed)
        self.rules_run = tuple(rules_run)

    def high(self):
        return [f for f in self.findings if f.severity == HIGH]

    def by_rule(self) -> dict:
        out: dict = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def by_severity(self) -> dict:
        out: dict = {}
        for f in self.findings:
            out[f.severity] = out.get(f.severity, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "program": self.name,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [
                {"finding": f.to_dict(), "reason": e.reason}
                for f, e in self.suppressed
            ],
            "by_rule": self.by_rule(),
            "high_total": len(self.high()),
        }

    def render(self) -> str:
        lines = [f"== {self.name}: {len(self.findings)} finding(s), "
                 f"{len(self.suppressed)} allowlisted =="]
        order = {HIGH: 0, WARN: 1, INFO: 2}
        for f in sorted(self.findings, key=lambda f: order[f.severity]):
            lines.append("  " + f.render().replace("\n", "\n  "))
        for f, e in self.suppressed:
            lines.append(f"  [allowlisted] {f.rule}: {f.message}")
            lines.append(f"      reason: {e.reason}")
        if not self.findings and not self.suppressed:
            lines.append("  clean")
        return "\n".join(lines)


# ------------------------------------------------------------------ walking
def _sub_jaxprs(params):
    """(tag, ClosedJaxpr|Jaxpr) pairs hiding in an equation's params —
    pjit/scan ('jaxpr'), while ('cond_jaxpr'/'body_jaxpr'), cond
    ('branches'), shard_map (open 'jaxpr'), custom_* calls, remat, etc.
    Generic over param names so new primitives keep walking."""
    found = []
    for k, v in params.items():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for i, item in enumerate(vs):
            if isinstance(item, (jax.core.Jaxpr, jax.core.ClosedJaxpr)):
                tag = k if len(vs) == 1 else f"{k}[{i}]"
                found.append((tag, item))
    return found


def _as_open(j):
    return j.jaxpr if isinstance(j, jax.core.ClosedJaxpr) else j


def _eqn_scope(eqn, scope):
    """Axis names brought into scope by this equation (shard_map mesh,
    pmap axis_name)."""
    name = eqn.primitive.name
    extra = ()
    if name == "shard_map":
        mesh = eqn.params.get("mesh")
        axes = getattr(mesh, "axis_names", None)
        if axes:
            extra = tuple(a for a in axes if isinstance(a, str))
    elif name == "xla_pmap":
        ax = eqn.params.get("axis_name")
        if isinstance(ax, str):
            extra = (ax,)
    return scope + extra if extra else scope


def iter_eqns(closed_jaxpr):
    """Yield (eqn, stack, axis_scope) over the whole program, recursing into
    every sub-jaxpr. ``stack`` is a tuple like ('pjit:step_fn', 'scan');
    ``axis_scope`` the mesh/pmap axis names bound at that point."""

    def walk(jaxpr, stack, scope):
        for eqn in jaxpr.eqns:
            yield eqn, stack, scope
            subs = _sub_jaxprs(eqn.params)
            if not subs:
                continue
            name = eqn.primitive.name
            label = name
            if name in ("pjit", "closed_call", "core_call", "custom_vjp_call",
                        "custom_jvp_call", "remat", "checkpoint"):
                label = f"{name}:{eqn.params.get('name', '')}".rstrip(":")
            inner_scope = _eqn_scope(eqn, scope)
            for tag, sub in subs:
                sub_label = label if len(subs) == 1 else f"{label}/{tag}"
                yield from walk(_as_open(sub), stack + (sub_label,),
                                inner_scope)

    yield from walk(closed_jaxpr.jaxpr, (), ())


def iter_consts(closed_jaxpr):
    """Yield (constvar, value, stack) for every captured constant, including
    those hoisted into nested ClosedJaxprs (jit closures land there)."""

    def walk(closed, stack):
        if isinstance(closed, jax.core.ClosedJaxpr):
            jaxpr = closed.jaxpr
            for var, val in zip(jaxpr.constvars, closed.consts):
                yield var, val, stack
        else:
            jaxpr = closed
        for eqn in jaxpr.eqns:
            for tag, sub in _sub_jaxprs(eqn.params):
                yield from walk(sub, stack + (f"{eqn.primitive.name}",))

    yield from walk(closed_jaxpr, ())


def source_of(eqn) -> str:
    """User-frame provenance of an equation, 'file:line (fn)' or ''."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return ""
        fn = getattr(frame, "function_name", "") or ""
        return (f"{frame.file_name}:{frame.start_line}"
                + (f" ({fn})" if fn else ""))
    except Exception:
        return ""


# ------------------------------------------------------------ rule running
def _run_rules(prog, rules, allowlist):
    from .rules import RULES

    selected = RULES if rules is None else {
        r: RULES[r] for r in rules
    }
    findings = []
    for rule_id, rule_fn in selected.items():
        try:
            got = list(rule_fn(prog))
        except Exception as e:  # a broken rule must not break the caller
            got = [Finding("rule-error", INFO,
                           f"rule {rule_id} crashed: {e!r}",
                           subject=prog.name)]
        cap = prog.thresholds.max_findings_per_rule
        if len(got) > cap:
            got = got[:cap] + [Finding(
                rule_id, got[cap].severity,
                f"... {len(got) - cap} more {rule_id} finding(s) truncated",
                subject=prog.name)]
        for f in got:
            f.subject = f.subject or prog.name
        findings.extend(got)
    if allowlist is None:
        allowlist = BUILTIN_ALLOWLIST
    try:
        backend = jax.default_backend()
    except Exception:
        backend = ""
    kept, suppressed = allowlist.apply(findings, backend)
    return Report(prog.name, kept, suppressed, tuple(selected))


# ------------------------------------------------------------- entry points
def _flat_inputs(args, kwargs, invars, donated_flags, arg_labels=None):
    """Pair flattened (args, kwargs) tree paths with the jaxpr's input avals
    (same flatten order) and per-invar donation flags."""
    leaves, _ = jax.tree_util.tree_flatten_with_path((args, kwargs))
    infos = []
    for i, v in enumerate(invars):
        label = f"arg[{i}]"
        if i < len(leaves):
            path, _val = leaves[i]
            # paths look like [0][1]['w']; strip the (args, kwargs) pair
            # index and optionally swap the positional index for a name
            inner = path[1:]
            label = jax.tree_util.keystr(inner) or jax.tree_util.keystr(path)
            if (arg_labels is not None and inner
                    and getattr(path[0], "idx", None) == 0):
                idx = getattr(inner[0], "idx", None)
                if idx is not None and idx < len(arg_labels):
                    label = (arg_labels[idx]
                             + jax.tree_util.keystr(inner[1:]))
        donated = donated_flags[i] if donated_flags is not None else None
        infos.append(InputInfo(label, v.aval, donated))
    return infos


def _traceable_leaf(val) -> bool:
    return (hasattr(val, "shape") or hasattr(val, "_value")
            or isinstance(val, (int, float, complex, bool)))


def _is_static_arg(val) -> bool:
    """A top-level argument is static (jit would require static_argnums)
    when any of its leaves cannot be traced as an array."""
    leaves = jax.tree_util.tree_leaves(val)
    if not leaves:
        return False  # empty containers trace fine
    return not all(_traceable_leaf(v) for v in leaves)


def _split_static(args, kwargs):
    """Partition into (dynamic args/kwargs, static {label: value}) and a
    caller that re-merges statics at their original positions — make_jaxpr
    abstractifies every argument it is handed, so static values must be
    closed over instead."""
    static = {}
    dyn_args, static_pos = [], {}
    for i, a in enumerate(args):
        if _is_static_arg(a):
            static_pos[i] = a
            static[f"[{i}]"] = a
        else:
            dyn_args.append(a)
    dyn_kwargs, static_kw = {}, {}
    for k, v in kwargs.items():
        if _is_static_arg(v):
            static_kw[k] = v
            static[f"['{k}']"] = v
        else:
            dyn_kwargs[k] = v

    def merge(dyn):
        full, it = [], iter(dyn)
        for i in range(len(args)):
            full.append(static_pos[i] if i in static_pos else next(it))
        return tuple(full)

    return tuple(dyn_args), dyn_kwargs, static, static_kw, merge


def analyze(fn, *args, _name=None, _mesh_axes=None, _hot=True,
            _donate_argnums=None, _thresholds=None, _allowlist=None,
            _rules=None, _arg_labels=None, _compiled=None, **kwargs):
    """Trace ``fn(*args, **kwargs)`` abstractly and lint the program.

    Keyword knobs are underscore-prefixed so they can never collide with the
    analyzed function's own kwargs. ``_donate_argnums`` declares donation for
    non-jitted callables (jitted ones carry it in their pjit equation);
    ``_mesh_axes`` declares the deployment mesh axis names the
    collective-axis rule validates against; ``_hot=False`` relaxes the
    host-sync rule to warnings (the program is not a per-step hot path).
    """
    dyn_args, dyn_kwargs, static_args, static_kw, merge = _split_static(
        args, kwargs)
    # Tensors are registered pytrees: make_jaxpr flattens them itself, and
    # functions written over Tensors (TrainStep's step_fn) need them intact
    raw_args, raw_kwargs = dyn_args, dyn_kwargs
    if static_args:
        def traced_fn(*dyn, **kw):
            return fn(*merge(dyn), **dict(kw, **static_kw))
    else:
        traced_fn = fn
    name = _name or getattr(fn, "__name__", None) or repr(fn)
    try:
        closed = jax.make_jaxpr(traced_fn)(*raw_args, **raw_kwargs)
    except Exception as e:
        # an unhashable static argument (itself a finding) aborts tracing;
        # report what can be judged without a jaxpr instead of raising
        from .findings import INFO as _INFO
        from .rules import static_arg_findings

        findings = static_arg_findings(static_args)
        findings.append(Finding(
            "rule-error", _INFO,
            f"program failed to trace, jaxpr rules skipped: {e!r}"[:300],
            subject=name))
        for f in findings:
            f.subject = f.subject or name
        return Report(name, findings, [], ("recompile-hazard",))

    donated = None
    n_in = len(closed.jaxpr.invars)
    eqns = closed.jaxpr.eqns
    if (len(eqns) == 1 and eqns[0].primitive.name == "pjit"
            and "donated_invars" in eqns[0].params):
        # map per-eqn-operand flags back onto the outer invars (operand
        # order can differ from invar order when args are unused)
        flag_of = {v: d for v, d in zip(eqns[0].invars,
                                        eqns[0].params["donated_invars"])
                   if not isinstance(v, jax.core.Literal)}
        donated = tuple(flag_of.get(v, False) for v in closed.jaxpr.invars)
    elif _donate_argnums is not None:
        dn = set(_donate_argnums)
        flags = []
        for i, a in enumerate(dyn_args):
            flags.extend([i in dn] * len(jax.tree_util.tree_leaves(a)))
        flags.extend([False] * len(jax.tree_util.tree_leaves(dyn_kwargs)))
        donated = tuple(flags) if len(flags) == n_in else None

    inputs = _flat_inputs(dyn_args, dyn_kwargs, closed.jaxpr.invars, donated,
                          arg_labels=_arg_labels)
    prog = Program(name, closed, inputs, mesh_axes=_mesh_axes, hot=_hot,
                   static_args=static_args, compiled=_compiled,
                   thresholds=_thresholds)
    return _run_rules(prog, _rules, _allowlist)


def analyze_jaxpr(closed_jaxpr, *, donated=None, arg_names=None, name="jaxpr",
                  mesh_axes=None, hot=True, thresholds=None, allowlist=None,
                  rules=None, compiled=None):
    """Lint an already-traced ``ClosedJaxpr``. ``donated`` is an optional
    per-invar tuple of flags; ``arg_names`` optional per-invar labels."""
    invars = closed_jaxpr.jaxpr.invars
    inputs = []
    for i, v in enumerate(invars):
        label = (arg_names[i] if arg_names is not None and i < len(arg_names)
                 else f"arg[{i}]")
        flag = donated[i] if donated is not None and i < len(donated) else None
        inputs.append(InputInfo(label, v.aval, flag))
    prog = Program(name, closed_jaxpr, inputs, mesh_axes=mesh_axes, hot=hot,
                   thresholds=thresholds, compiled=compiled)
    return _run_rules(prog, rules, allowlist)


def analyze_lowered(lowered, *, name=None, hot=True, thresholds=None,
                    allowlist=None):
    """Lint a ``jax.stages.Lowered``: donation judged from ``args_info`` +
    the StableHLO main signature, host-sync and constant bloat from the
    module text. Reduced rule set (the jaxpr rules need ``analyze``)."""
    from .rules import lint_lowered

    th = thresholds or Thresholds()
    name = name or "lowered"
    findings = lint_lowered(lowered, name=name, hot=hot, thresholds=th)
    if allowlist is None:
        allowlist = BUILTIN_ALLOWLIST
    try:
        backend = jax.default_backend()
    except Exception:
        backend = ""
    kept, suppressed = allowlist.apply(findings, backend)
    return Report(name, kept, suppressed,
                  ("donation-miss", "host-sync", "constant-bloat"))


def analyze_train_step(step, *args, name=None, thresholds=None,
                       allowlist=None, rules=None, mesh_axes=None, **kwargs):
    """Lint a ``jit/train.py:TrainStep`` over the exact traced-input tuple
    its next ``__call__`` would consume (peeked — no optimizer bookkeeping
    is mutated, nothing executes). The compiled AOT executable, when primed,
    rides along so donation findings can cross-check
    ``observability.xla.memory_stats`` alias bytes."""
    _, traced = step._prep_inputs(advance=False)
    if name is None:
        name = f"train_step:{type(step.model).__name__}"
    return analyze(
        step._jitted, *traced, args, kwargs,
        _name=name, _mesh_axes=mesh_axes, _hot=True,
        _thresholds=thresholds, _allowlist=allowlist, _rules=rules,
        _compiled=getattr(step, "_compiled", None),
        _arg_labels=("state", "acc_state", "step_i", "lr", "rng_key",
                     "batch", "batch_kwargs"))
