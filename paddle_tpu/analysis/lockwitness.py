"""Runtime lock witness: the dynamic half of the thread lint.

The static pass (``analysis/threads.py``) proves properties of the SOURCE —
which locks *can* be acquired while which are held, which fields *should* be
guarded. This module witnesses what actually happens at runtime, in the style
of Eraser's lockset discipline (Savage et al., SOSP 1997): every lock the
runtime modules create goes through :func:`make_lock` / :func:`make_rlock`,
which normally hand back a plain ``threading`` lock with ZERO overhead — but
while a :class:`LockWitness` is activated (the chaos suite does this for
every fault-injection test), each acquisition records

* the **acquisition-order edge** ``held -> acquired`` (per thread, with the
  acquiring source line), and
* an **inversion** the moment some thread acquires ``A`` while holding ``B``
  after any thread acquired ``B`` while holding ``A`` — the classic
  two-thread deadlock witnessed live, even when the interleaving happened to
  not deadlock this run;

plus an Eraser-style **lockset per shared field** for code that calls
:meth:`LockWitness.note_field` at its shared accesses: the candidate lockset
is the intersection of the locksets across all accesses, and an empty
intersection after accesses from two distinct threads is a race candidate.

``check_static(static_edges)`` closes the loop with the static pass: the
union of witnessed and statically-inferred edges must still be acyclic, so a
runtime ordering that *combined with* a path the tests never exercised would
deadlock is caught too (the chaos suite asserts this with
``analysis.threads.lock_order_graph()``).

Edges are keyed by lock NAME (``"PagedKVCache._lock"``), aggregating
instances of the same class; nested acquisition of two same-named locks of
different instances is skipped rather than reported (per-instance handover
patterns would otherwise self-report). Re-entrant acquisition of the same
RLock instance records nothing.
"""
from __future__ import annotations

import threading

__all__ = ["LockWitness", "make_lock", "make_rlock", "activate",
           "deactivate", "active_witness"]

_ACTIVE: "LockWitness | None" = None
_ACTIVE_LOCK = threading.Lock()


def activate(witness: "LockWitness") -> "LockWitness":
    """Make `witness` the process-wide witness: every lock subsequently
    created through make_lock/make_rlock is wrapped. Returns the witness."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = witness
    return witness


def deactivate() -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = None


def active_witness() -> "LockWitness | None":
    return _ACTIVE


def make_lock(name: str):
    """A ``threading.Lock`` for production use, witness-wrapped while a
    LockWitness is active (the chaos suite); a plain lock otherwise."""
    base = threading.Lock()
    w = _ACTIVE
    return base if w is None else _WitnessedLock(base, name, w)


def make_rlock(name: str):
    """Re-entrant twin of :func:`make_lock`."""
    base = threading.RLock()
    w = _ACTIVE
    return base if w is None else _WitnessedLock(base, name, w)


class _Held:
    """One thread's current lock stack: [(wrapper, count)]."""

    __slots__ = ("stack",)

    def __init__(self):
        self.stack = []     # list of [wrapper, reentry_count]


class LockWitness:
    """Collects acquisition-order edges, inversions, and field locksets."""

    def __init__(self):
        self._mu = threading.Lock()
        self._tls = threading.local()
        # (held_name, acquired_name) -> "file:line" of the first acquisition
        self.edges: dict = {}
        # [{"edge": (a, b), "site": ..., "prior_site": ...}, ...]
        self.inversions: list = []
        self.acquisitions = 0
        # field -> {"lockset": frozenset | None (= not yet seen),
        #           "threads": set, "races": [...]}
        self._fields: dict = {}

    # ------------------------------------------------------------- recording
    def _held(self) -> _Held:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = _Held()
        return h

    @staticmethod
    def _site():
        import sys

        # walk out of this module's frames to the caller's acquire site
        f = sys._getframe(1)
        while f is not None and f.f_globals.get("__name__") == __name__:
            f = f.f_back
        if f is None:
            return "?"
        return f"{f.f_code.co_filename}:{f.f_lineno}"

    def _on_acquired(self, wrapper):
        held = self._held()
        for entry in held.stack:
            if entry[0] is wrapper:         # re-entrant: no edge, count up
                entry[1] += 1
                return
        site = self._site()
        with self._mu:
            self.acquisitions += 1
            for entry in held.stack:
                a = entry[0].name
                b = wrapper.name
                if a == b:      # same-named pair of different instances:
                    continue    # aggregation would self-report; skip
                if (a, b) not in self.edges:
                    self.edges[(a, b)] = site
                if (b, a) in self.edges:
                    self.inversions.append({
                        "edge": (a, b), "site": site,
                        "prior_site": self.edges[(b, a)]})
        held.stack.append([wrapper, 1])

    def _on_released(self, wrapper):
        held = self._held()
        for i in range(len(held.stack) - 1, -1, -1):
            if held.stack[i][0] is wrapper:
                held.stack[i][1] -= 1
                if held.stack[i][1] == 0:
                    del held.stack[i]
                return

    # ---------------------------------------------------------- field lockset
    def note_field(self, field: str):
        """Eraser lockset refinement for one shared-field access: intersect
        the candidate lockset with the locks the calling thread holds NOW.
        An empty candidate after accesses from >= 2 threads is recorded in
        ``races`` (the access that emptied it carries the site)."""
        held = frozenset(e[0].name for e in self._held().stack)
        tid = threading.get_ident()
        with self._mu:
            st = self._fields.setdefault(
                field, {"lockset": None, "threads": set(), "races": []})
            st["threads"].add(tid)
            st["lockset"] = (held if st["lockset"] is None
                             else st["lockset"] & held)
            if not st["lockset"] and len(st["threads"]) > 1:
                st["races"].append({"field": field, "site": self._site()})

    def field_lockset(self, field: str):
        with self._mu:
            st = self._fields.get(field)
            return None if st is None else st["lockset"]

    def race_candidates(self) -> list:
        with self._mu:
            return [r for st in self._fields.values() for r in st["races"]]

    # ------------------------------------------------------------ validation
    def check_static(self, static_edges) -> list:
        """Cycles in (witnessed ∪ static) acquisition-order edges — orderings
        that would deadlock against a path the tests never interleaved.
        `static_edges` is an iterable of (a, b) pairs (or a dict keyed by
        them, e.g. ``analysis.threads.lock_order_graph()``). Returns a list
        of cycles (each a list of lock names); empty means consistent."""
        adj: dict = {}
        with self._mu:
            pairs = set(self.edges)
        pairs.update(tuple(e) for e in static_edges)
        for a, b in pairs:
            adj.setdefault(a, set()).add(b)
        return _find_cycles(adj)

    def summary(self) -> dict:
        with self._mu:
            return {"acquisitions": self.acquisitions,
                    "edges": len(self.edges),
                    "inversions": list(self.inversions),
                    "race_candidates": [r for st in self._fields.values()
                                        for r in st["races"]]}


def _find_cycles(adj: dict) -> list:
    """Distinct elementary cycles (one representative per SCC loop) via
    iterative DFS; enough to NAME the deadlock, not enumerate every path."""
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in adj}
    cycles, stack = [], []

    def dfs(start):
        path = [start]
        iters = [iter(adj.get(start, ()))]
        color[start] = GREY
        while path:
            try:
                nxt = next(iters[-1])
            except StopIteration:
                color[path[-1]] = BLACK
                path.pop()
                iters.pop()
                continue
            c = color.get(nxt, WHITE)
            if c == GREY:
                cycles.append(path[path.index(nxt):] + [nxt])
            elif c == WHITE:
                color[nxt] = GREY
                path.append(nxt)
                iters.append(iter(adj.get(nxt, ())))

    for node in list(adj):
        if color.get(node, WHITE) == WHITE:
            dfs(node)
    # canonicalize (rotate to min element) and dedupe
    seen, out = set(), []
    for cyc in cycles:
        body = cyc[:-1]
        i = body.index(min(body))
        canon = tuple(body[i:] + body[:i])
        if canon not in seen:
            seen.add(canon)
            out.append(list(canon) + [canon[0]])
    return out


class _WitnessedLock:
    """Context-manager/acquire-release proxy feeding a LockWitness. Supports
    both Lock and RLock semantics (re-entrancy tracked by instance)."""

    __slots__ = ("_base", "name", "_w")

    def __init__(self, base, name, witness):
        self._base = base
        self.name = name
        self._w = witness

    def acquire(self, blocking=True, timeout=-1):
        ok = self._base.acquire(blocking, timeout)
        if ok:
            self._w._on_acquired(self)
        return ok

    def release(self):
        self._w._on_released(self)
        self._base.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._base.locked()

    def __repr__(self):
        return f"WitnessedLock({self.name})"
