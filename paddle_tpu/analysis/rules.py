"""The graph-lint rule catalog (see docs/ANALYSIS.md for the full taxonomy).

Six rules, each targeting one way a traced-and-compiled program silently
burns money on a TPU:

* ``donation-miss``       — a large aliasable input (params, optimizer
  state, KV pools) is consumed but not donated: XLA holds input AND output
  copies, doubling that buffer's HBM. Cross-checked against the compiled
  executable's ``memory_stats`` alias bytes when one is attached.
* ``dtype-upcast``        — an f32/f64 ``convert_element_type`` chain feeds
  an MXU op (dot/conv) whose operand was bf16/f16: the matmul runs at half
  (or an eighth, f64) MXU throughput for no numerics the caller asked for.
  Any float64 anywhere is flagged too (accidental weak-type promotion).
* ``host-sync``           — ``pure_callback``/``io_callback``/
  ``debug_callback`` inside a hot program (TrainStep, decode): each one
  forces a device→host round trip per step.
* ``constant-bloat``      — big arrays baked into the program as constants:
  they live in HBM per-executable, re-stage on every compile, and hash into
  the trace fingerprint (slow retraces).
* ``recompile-hazard``    — argument/closure patterns that make XLA rebuild
  the program per step: weak-typed Python scalars (alternating with NumPy
  scalars refingerprints — the same aval-fingerprint machinery as the
  StepMonitor recompilation sentinel), identity-hashed or unhashable
  static arguments.
* ``collective-axis``     — psum/ppermute/all_gather axis names validated
  against the enclosing shard_map/pmap scope and the declared deployment
  mesh axes.

Rules are pure functions ``rule(Program) -> [Finding]`` registered in
``RULES``; the runner in ``core.py`` caps, attributes and allowlists them.
"""
from __future__ import annotations

import re

import jax
import jax.numpy as jnp
import numpy as np

from .core import (
    Thresholds,
    _as_open,
    _sub_jaxprs,
    fmt_bytes,
    iter_consts,
    iter_eqns,
    source_of,
)
from .findings import HIGH, WARN, Finding

__all__ = ["RULES", "lint_lowered"]

NARROW = {jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)}
WIDE = {jnp.dtype(jnp.float32), jnp.dtype(jnp.float64)}
MXU_PRIMS = {"dot_general", "conv_general_dilated"}
# shape/layout ops that carry an upcast value unchanged into a matmul
LAYOUT_PRIMS = {"transpose", "reshape", "broadcast_in_dim", "squeeze",
                "slice", "dynamic_slice", "rev", "copy", "gather",
                "concatenate"}
CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback"}
COLLECTIVE_PRIMS = {"psum", "psum2", "pbroadcast", "pmax", "pmin",
                    "ppermute", "all_gather", "all_to_all", "psum_scatter",
                    "pgather", "axis_index"}


def _is_float(dtype) -> bool:
    try:
        return jnp.issubdtype(dtype, jnp.floating)
    except Exception:
        return False


def _np_dtype(dtype):
    """numpy dtype or None for extended dtypes (PRNG keys) that
    ``jnp.dtype`` refuses."""
    try:
        return jnp.dtype(dtype)
    except Exception:
        return None


# ------------------------------------------------------------ donation-miss
def rule_donation_miss(prog):
    """Large array inputs consumed but not donated while a same-shaped
    output exists (the state-in/state-out pattern XLA could alias)."""
    if all(i.donated is None for i in prog.inputs):
        return []  # not a jitted program and no donate_argnums declared
    th = prog.thresholds.donation_min_bytes
    out_shapes = {}
    for v in prog.jaxpr.outvars:
        aval = getattr(v, "aval", None)
        dt = _np_dtype(getattr(aval, "dtype", None))
        if aval is not None and dt is not None \
                and getattr(aval, "shape", None) is not None:
            key = (tuple(aval.shape), dt.name)
            out_shapes[key] = out_shapes.get(key, 0) + 1
    findings = []
    for info in prog.inputs:
        if info.donated or info.donated is None:
            continue
        nbytes = info.nbytes
        dt = _np_dtype(getattr(info.aval, "dtype", None))
        if nbytes < th or dt is None:
            continue
        key = (tuple(info.aval.shape), dt.name)
        if out_shapes.get(key, 0) <= 0:
            continue
        out_shapes[key] -= 1  # each output aliases at most one input
        findings.append(Finding(
            "donation-miss", HIGH,
            f"input {info.path} ({fmt_bytes(nbytes)}, {dt.name}"
            f"{list(info.aval.shape)}) is consumed and a same-shaped output "
            f"exists, but the buffer is not donated — XLA holds two copies",
            where=info.path,
            remediation="add the argument to donate_argnums (jax.jit) so "
                        "XLA aliases it in place; saves "
                        f"{fmt_bytes(nbytes)} of HBM"))
    # cross-check declared donation against what the executable actually
    # aliased (observability.xla memory_stats)
    if prog.compiled is not None and any(i.donated for i in prog.inputs):
        from ..observability.xla import memory_stats

        mem = memory_stats(prog.compiled)
        donated_bytes = sum(i.nbytes for i in prog.inputs if i.donated)
        if mem and donated_bytes >= th and mem.get("alias_bytes", 0) == 0:
            findings.append(Finding(
                "donation-miss", WARN,
                f"{fmt_bytes(donated_bytes)} declared donated but the "
                "compiled executable aliases 0 bytes "
                "(memory_stats.alias_bytes) — this backend ignores "
                "donation, the memory plan still holds both copies",
                remediation="expected on CPU; on TPU investigate why XLA "
                            "refused the aliasing (dtype/layout mismatch "
                            "between the input and its would-be output)"))
    return findings


# ------------------------------------------------------------- dtype-upcast
def _strong_f64(aval) -> bool:
    """A float64 aval that is genuinely f64 compute: weak-typed scalars
    (Python floats under global x64) demote on promotion and are the
    recompile-hazard rule's business, not this one's."""
    if aval is None or getattr(aval, "dtype", None) is None:
        return False
    dt = _np_dtype(aval.dtype)
    if dt is None or dt != jnp.dtype(jnp.float64):
        return False
    return not (getattr(aval, "weak_type", False)
                and getattr(aval, "shape", ()) == ())


def _taint_walk(jaxpr, tainted, findings, stack, seen_f64):
    """Track values that are pure upcasts of narrow tensors; flag MXU ops
    consuming them. `tainted` maps Var -> source dtype name."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        # strong float64 anywhere is its own hazard (weak-type promotion)
        for v in eqn.outvars:
            if not seen_f64 and _strong_f64(getattr(v, "aval", None)):
                seen_f64.append(source_of(eqn) or name)
        if name in MXU_PRIMS:
            hit = [tainted[v] for v in eqn.invars
                   if not isinstance(v, jax.core.Literal) and v in tainted]
            if hit:
                out_dt = _np_dtype(eqn.outvars[0].aval.dtype)
                findings.append(Finding(
                    "dtype-upcast", HIGH,
                    f"{name} consumes operand(s) upcast from {hit[0]} — the "
                    f"matmul runs in "
                    f"{out_dt.name if out_dt is not None else '?'} at half "
                    "MXU throughput" + (f" (inside {'/'.join(stack)})"
                                        if stack else ""),
                    where=source_of(eqn),
                    remediation="keep the operands in their narrow dtype "
                                "(drop the .astype) or, if f32 accumulation "
                                "is the goal, use preferred_element_type "
                                "instead of upcasting the inputs"))
        if name == "convert_element_type":
            src = eqn.invars[0]
            src_aval = getattr(src, "aval", None)
            dst = _np_dtype(eqn.params.get("new_dtype",
                                           eqn.outvars[0].aval.dtype))
            if (dst is not None and src_aval is not None
                    and _is_float(src_aval.dtype)):
                if (jnp.dtype(src_aval.dtype) in NARROW and dst in WIDE):
                    tainted[eqn.outvars[0]] = jnp.dtype(src_aval.dtype).name
                elif (not isinstance(src, jax.core.Literal)
                      and src in tainted and dst in NARROW):
                    pass  # downcast back: taint does not propagate
                elif (not isinstance(src, jax.core.Literal)
                      and src in tainted):
                    tainted[eqn.outvars[0]] = tainted[src]
        elif name in LAYOUT_PRIMS:
            src = eqn.invars[0]
            if not isinstance(src, jax.core.Literal) and src in tainted:
                tainted[eqn.outvars[0]] = tainted[src]
        # recurse with taint mapped across the sub-jaxpr boundary
        subs = _sub_jaxprs(eqn.params)
        for tag, sub in subs:
            open_sub = _as_open(sub)
            inner = {}
            n_in, n_sub = len(eqn.invars), len(open_sub.invars)
            if n_sub == n_in:
                pairs = zip(eqn.invars, open_sub.invars)
            elif n_sub == n_in - 1:  # cond/switch: index operand first
                pairs = zip(eqn.invars[1:], open_sub.invars)
            else:
                pairs = ()
            for outer_v, inner_v in pairs:
                if (not isinstance(outer_v, jax.core.Literal)
                        and outer_v in tainted):
                    inner[inner_v] = tainted[outer_v]
            _taint_walk(open_sub, inner, findings, stack + (name,), seen_f64)


def rule_dtype_upcast(prog):
    """f32/f64 upcast chains feeding MXU ops inside bf16/f16 regions, and
    any float64 leakage (weak-type promotion)."""
    findings: list = []
    seen_f64: list = []
    _taint_walk(prog.jaxpr, {}, findings, (), seen_f64)
    for v in list(prog.jaxpr.invars) + list(prog.jaxpr.constvars):
        if not seen_f64 and _strong_f64(getattr(v, "aval", None)):
            seen_f64.append("program input/constant")
    if seen_f64:
        findings.append(Finding(
            "dtype-upcast", HIGH,
            f"float64 appears in the program (first at {seen_f64[0]}) — "
            "on TPU f64 matmuls run ~8x slower than bf16 and usually mean "
            "an accidental weak-type promotion (Python float * array)",
            where=seen_f64[0],
            remediation="cast to float32/bfloat16 explicitly, or keep "
                        "jax_enable_x64 off"))
    return findings


# --------------------------------------------------------------- host-sync
def rule_host_sync(prog):
    """Host callbacks inside compiled programs: each is a device->host
    round trip per execution (per STEP in a train/decode program, per scan
    iteration when inside the loop body)."""
    findings = []
    for eqn, stack, _scope in iter_eqns(prog.closed_jaxpr):
        name = eqn.primitive.name
        if name not in CALLBACK_PRIMS and "callback" not in name:
            continue
        in_loop = any(s.startswith(("scan", "while")) for s in stack)
        sev = HIGH if (prog.hot or in_loop) else WARN
        cb = eqn.params.get("callback", None)
        cb_name = getattr(cb, "__name__", None) or getattr(
            getattr(cb, "callback_func", None), "__name__", "") or ""
        where_note = (" inside the compiled loop body" if in_loop
                      else " in a hot-path program" if prog.hot else "")
        findings.append(Finding(
            "host-sync", sev,
            f"{name}{f' ({cb_name})' if cb_name else ''}{where_note}"
            f"{' [' + '/'.join(stack) + ']' if stack else ''} forces a "
            "device→host sync every execution",
            where=source_of(eqn),
            remediation="remove the callback from the step program (fetch "
                        "results outside, or gate debug prints behind an "
                        "eager-only flag); io_callback/debug_callback also "
                        "block XLA's async dispatch"))
    return findings


# ------------------------------------------------------------ constant-bloat
def rule_constant_bloat(prog):
    """Arrays baked into the graph as constants above the byte thresholds:
    HBM cost per executable + trace-time hashing + re-staging per compile."""
    th = prog.thresholds
    findings = []
    for var, val, stack in iter_consts(prog.closed_jaxpr):
        try:
            nbytes = int(getattr(val, "nbytes", 0))
        except Exception:
            nbytes = 0
        if nbytes < th.const_warn_bytes:
            continue
        sev = HIGH if nbytes >= th.const_high_bytes else WARN
        shape = tuple(getattr(val, "shape", ()))
        dtype = getattr(val, "dtype", "?")
        findings.append(Finding(
            "constant-bloat", sev,
            f"constant {dtype}{list(shape)} ({fmt_bytes(nbytes)}) is baked "
            f"into the program"
            f"{' [' + '/'.join(stack) + ']' if stack else ''} — it occupies "
            "HBM per executable, hashes into every trace, and re-stages on "
            "each compile",
            where="/".join(stack) or "top-level consts",
            remediation="pass the array as an argument (jit will stage it "
                        "once as an input buffer) instead of closing over "
                        "it"))
    return findings


# ---------------------------------------------------------- recompile-hazard
def _default_hash_identity(v) -> bool:
    t = type(v)
    return (getattr(t, "__hash__", None) is object.__hash__
            and getattr(t, "__eq__", None) is object.__eq__)


def rule_recompile_hazard(prog):
    """Argument/closure patterns that re-fingerprint the program per call —
    the same aval-fingerprint machinery the StepMonitor recompilation
    sentinel counts at runtime, caught at trace time instead."""
    findings = []
    inputs = prog.inputs
    for i, v in enumerate(prog.jaxpr.invars):
        aval = getattr(v, "aval", None)
        if aval is None:
            continue
        if getattr(aval, "weak_type", False) and aval.shape == ():
            label = inputs[i].path if i < len(inputs) else f"arg[{i}]"
            findings.append(Finding(
                "recompile-hazard", WARN,
                f"scalar argument {label} is weak-typed (traced from a "
                "Python scalar): alternating Python and NumPy/jnp scalars "
                "across calls changes the aval fingerprint and silently "
                "recompiles",
                where=label,
                remediation="pass a committed-dtype scalar "
                            "(jnp.asarray(x, jnp.float32)) consistently, or "
                            "hoist it to a closure constant if it never "
                            "changes"))
    for var, val, stack in iter_consts(prog.closed_jaxpr):
        aval = getattr(var, "aval", None)
        if (aval is not None and getattr(aval, "weak_type", False)
                and aval.shape == ()):
            findings.append(Finding(
                "recompile-hazard", WARN,
                "a Python scalar is closed over and baked as a weak-typed "
                f"constant (value {np.asarray(val).item()!r}"
                f"{' [' + '/'.join(stack) + ']' if stack else ''}): a "
                "closure rebuilt per step retraces, and a value change "
                "after the first trace is silently ignored",
                where="/".join(stack) or "top-level consts",
                remediation="pass the scalar as an argument, or inline it "
                            "as a literal if truly constant"))
    findings.extend(static_arg_findings(prog.static_args))
    return findings


def static_arg_findings(static_args):
    """The static-argument half of recompile-hazard, callable on its own:
    ``analyze`` falls back to it when an unhashable static argument makes
    the program refuse to trace at all."""
    findings = []
    for label, v in static_args.items():
        try:
            hash(v)
        except TypeError:
            findings.append(Finding(
                "recompile-hazard", HIGH,
                f"static argument {label} ({type(v).__name__}) is "
                "unhashable — jit rejects it, and hashable wrappers built "
                "per call recompile every step",
                where=label,
                remediation="use a hashable static value (tuple instead of "
                            "list, frozen dataclass instead of dict)"))
            continue
        if _default_hash_identity(v):
            findings.append(Finding(
                "recompile-hazard", HIGH,
                f"static argument {label} ({type(v).__name__}) hashes by "
                "object identity — a fresh instance per call fingerprints "
                "differently and compiles a NEW program every step",
                where=label,
                remediation="define __hash__/__eq__ over the fields that "
                            "matter, or pass a stable singleton"))
    return findings


# ----------------------------------------------------------- collective-axis
def _axis_names(params):
    axes = params.get("axes", params.get("axis_name", ()))
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def rule_collective_axis(prog):
    """Collective axis names must be bound by an enclosing shard_map/pmap
    and — when the caller declares the deployment mesh — exist on it."""
    declared = prog.mesh_axes
    findings = []
    for eqn, stack, scope in iter_eqns(prog.closed_jaxpr):
        name = eqn.primitive.name
        if name == "shard_map" and declared is not None:
            mesh = eqn.params.get("mesh")
            for ax in getattr(mesh, "axis_names", ()) or ():
                if isinstance(ax, str) and ax not in declared:
                    findings.append(Finding(
                        "collective-axis", HIGH,
                        f"shard_map binds mesh axis '{ax}' but the declared "
                        f"deployment mesh has axes {declared} — this "
                        "program cannot run on that mesh",
                        where=source_of(eqn),
                        remediation="rename the program's mesh axes to the "
                                    "deployment mesh's, or extend the mesh"))
        if name not in COLLECTIVE_PRIMS:
            continue
        for ax in _axis_names(eqn.params):
            if ax not in scope:
                findings.append(Finding(
                    "collective-axis", HIGH,
                    f"{name} uses axis '{ax}' which no enclosing "
                    f"shard_map/pmap binds (scope: {scope or '()'})",
                    where=source_of(eqn),
                    remediation="run the collective inside a shard_map "
                                "whose mesh defines the axis"))
            elif declared is not None and ax not in declared:
                findings.append(Finding(
                    "collective-axis", HIGH,
                    f"{name} reduces over axis '{ax}' but the declared "
                    f"deployment mesh has axes {declared}",
                    where=source_of(eqn),
                    remediation="align the collective's axis_name with the "
                                "deployment mesh axes"))
    return findings


RULES = {
    "donation-miss": rule_donation_miss,
    "dtype-upcast": rule_dtype_upcast,
    "host-sync": rule_host_sync,
    "constant-bloat": rule_constant_bloat,
    "recompile-hazard": rule_recompile_hazard,
    "collective-axis": rule_collective_axis,
}


# ------------------------------------------------------- lowered-text rules
_MLIR_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "i64": 8,
                     "ui64": 8, "i32": 4, "ui32": 4, "i16": 2, "ui16": 2,
                     "i8": 1, "ui8": 1, "i1": 1, "i4": 1, "ui4": 1}


def _mlir_dtype(dtype) -> str:
    name = jnp.dtype(dtype).name
    return {"float64": "f64", "float32": "f32", "bfloat16": "bf16",
            "float16": "f16", "int64": "i64", "int32": "i32",
            "int16": "i16", "int8": "i8", "uint8": "ui8",
            "bool": "i1"}.get(name, name)


def _tensor_type(shape, dtype) -> str:
    dims = "x".join(str(d) for d in shape)
    return f"tensor<{dims + 'x' if dims else ''}{_mlir_dtype(dtype)}>"


def _tensor_bytes(type_str) -> int:
    m = re.match(r"tensor<([0-9x]*)x?([a-z]+[0-9]+|i1)>", type_str)
    if not m:
        return 0
    dims, dt = m.groups()
    size = 1
    for d in filter(None, dims.split("x")):
        size *= int(d)
    return size * _MLIR_DTYPE_BYTES.get(dt, 4)


def lint_lowered(lowered, *, name, hot, thresholds: Thresholds):
    """The StableHLO-text subset of the rules for ``analyze_lowered``:
    donation (args_info + main signature), host-sync (callback custom
    calls), constant bloat (constant op tensor types)."""
    findings = []
    try:
        text = lowered.as_text()
    except Exception:
        text = ""
    # --- donation-miss from args_info + result types
    try:
        infos = jax.tree_util.tree_leaves(
            lowered.args_info, is_leaf=lambda l: hasattr(l, "donated"))
    except Exception:
        infos = []
    results = []
    m = re.search(r"func\.func public @main\((.*?)\)\s*->\s*\((.*?)\)\s*{",
                  text, re.S)
    if m:
        results = re.findall(r"tensor<[^>]+>", m.group(2))
    result_counts: dict = {}
    for r in results:
        result_counts[r] = result_counts.get(r, 0) + 1
    for i, info in enumerate(infos):
        if info.donated:
            continue
        tt = _tensor_type(info.shape, info.dtype)
        nbytes = _tensor_bytes(tt)
        if nbytes < thresholds.donation_min_bytes:
            continue
        if result_counts.get(tt, 0) <= 0:
            continue
        result_counts[tt] -= 1
        findings.append(Finding(
            "donation-miss", HIGH,
            f"lowered arg #{i} ({tt}, {fmt_bytes(nbytes)}) is not donated "
            "but a same-typed result exists — XLA holds two copies",
            where=f"args_info[{i}]", subject=name,
            remediation="add the argument to donate_argnums"))
    # --- host-sync from callback custom calls
    for ln in text.splitlines():
        if "custom_call" in ln and "callback" in ln:
            findings.append(Finding(
                "host-sync", HIGH if hot else WARN,
                "callback custom_call in the lowered module — a "
                "device→host sync every execution",
                where=ln.strip()[:160], subject=name,
                remediation="remove host callbacks from the compiled "
                            "program"))
    # --- constant-bloat from constant op types
    for m2 in re.finditer(
            r"stablehlo\.constant[^\n]*?:\s*(tensor<[^>]+>)", text):
        nbytes = _tensor_bytes(m2.group(1))
        if nbytes < thresholds.const_warn_bytes:
            continue
        sev = HIGH if nbytes >= thresholds.const_high_bytes else WARN
        findings.append(Finding(
            "constant-bloat", sev,
            f"constant {m2.group(1)} ({fmt_bytes(nbytes)}) baked into the "
            "lowered module",
            where="stablehlo.constant", subject=name,
            remediation="pass the array as an argument instead of closing "
                        "over it"))
    return findings
