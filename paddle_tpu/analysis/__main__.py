"""``python -m paddle_tpu.analysis`` — lint the bundled model zoo programs.

Exit status is the gate: 0 when every program is clean at high severity
(allowlisted findings are printed with their justification, not hidden),
1 when any un-allowlisted high-severity finding survives. Wire
``--self-check`` into CI next to the tier-1 tests; ``--json`` emits the
same findings-by-rule structure the bench ``graph_lint`` leg reports.
"""
from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="Graph lint over the bundled model zoo programs "
                    "(GPT/ResNet train steps, dense+paged decode).")
    parser.add_argument("--self-check", action="store_true",
                        help="lint the model zoo and exit non-zero on any "
                             "high-severity finding (the default behavior; "
                             "the flag exists for explicit CI wiring)")
    parser.add_argument("--programs", default=None,
                        help="comma-separated subset of zoo programs "
                             "(default: all)")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON object instead of text")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    from .rules import RULES

    if args.list_rules:
        for rule_id, fn in RULES.items():
            doc = (fn.__doc__ or "").strip().split("\n")[0]
            print(f"{rule_id:18s} {doc}")
        return 0

    from .zoo import ZOO_PROGRAMS, zoo_reports

    include = None
    if args.programs:
        include = [p.strip() for p in args.programs.split(",") if p.strip()]
        unknown = [p for p in include if p not in ZOO_PROGRAMS]
        if unknown:
            print(f"unknown program(s) {unknown}; available: "
                  f"{sorted(ZOO_PROGRAMS)}", file=sys.stderr)
            return 2

    reports = zoo_reports(include=include)
    high_total = sum(len(r.high()) for r in reports)
    if args.json:
        print(json.dumps({
            "programs": [r.to_dict() for r in reports],
            "high_total": high_total,
            "status": "ok" if high_total == 0 else "lint-high",
        }))
    else:
        for r in reports:
            print(r.render())
        print(f"-- {len(reports)} program(s), {high_total} high-severity "
              f"finding(s) -> {'CLEAN' if high_total == 0 else 'FAIL'}")
    return 0 if high_total == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
