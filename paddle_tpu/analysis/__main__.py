"""``python -m paddle_tpu.analysis`` — lint the zoo programs AND the host
runtime's own threading discipline.

Exit status is the gate: 0 when every zoo program is clean at high severity
AND the thread lint over the framework source reports zero un-allowlisted
high findings (allowlisted findings are printed with their justification,
not hidden); 1 otherwise. Wire ``--self-check`` into CI next to the tier-1
tests; ``--json`` emits the same findings-by-rule structure the bench
``graph_lint`` / ``thread_lint`` legs report.

``--programs a,b`` restricts to a zoo subset (graph lint only);
``--threads [PATH]`` runs ONLY the thread lint — over PATH (a file or
directory, every module treated as runtime: the seeded-violation fixture
mode) or, with no PATH, over the installed ``paddle_tpu`` package.

ISSUE-13 adds the compile-surface contract (analysis/compilesurface.py):
the full self-check lints it via the ``compile_surface`` zoo entry;
``--surface [PATH]`` runs ONLY that pass — strict fixture mode over PATH
(a generation-like ``.py`` source, a ``{"configs","manifest"}`` ``.json``
spec, or a directory of either) or the real tree when PATH is omitted;
``--manifest [CONFIG]`` prints the DERIVED program inventory as JSON (the
thing a deployment pastes into its declared manifest) for all shipped
serving configs, one of them by name, or a ServingConfig ``.json`` file.

ISSUE-14 adds the HBM residency contract (analysis/hbm.py): the full
self-check runs it via the ``hbm_residency`` zoo entry and appends the
stale-allowlist audit (builtin suppressions that matched nothing);
``--hbm [NAME|PATH]`` runs ONLY the residency pass — the smoke deployment
plan's residency table plus the four rules (optionally for one shipped
serving config by NAME), or strict fixture mode over a DeploymentPlan
``.json`` / ``make_program()`` ``.py`` / directory PATH.

ISSUE-20 adds the sharding-and-collective contract (analysis/comms.py):
the full self-check runs it via the ``comms_surface`` zoo entry (and its
builtin allowlist joins the stale audit); ``--comms [NAME|PATH]`` runs
ONLY that pass — the per-program collective table (every all-gather /
all-reduce / reduce-scatter / all-to-all / collective-permute GSPMD
compiled into the step programs, with bytes-on-wire) plus the five comms
rules, optionally for one step path by NAME (``prefill_chunk`` /
``decode_step`` / ``verify_step``), or strict fixture mode over a
``make_program()`` ``.py`` / comms-surface ``.json`` / directory PATH.
"""
from __future__ import annotations

import argparse
import json
import sys


def _print_manifest(spec=None) -> int:
    """``--manifest [CONFIG]``: resolve the config set, derive its closed
    program inventory through the extracted key schemas, and print the
    JSON a deployment declares (and AOTWarmup compiles)."""
    import os

    from .compilesurface import (CompileSurfaceError, ProgramManifest,
                                 ServingConfig, default_serving_configs,
                                 extract_key_schemas)

    if spec is None:
        configs = list(default_serving_configs())
    elif os.path.isfile(spec):
        with open(spec, "r") as fh:
            obj = json.load(fh)
        raw = obj if isinstance(obj, list) else obj.get("configs", [obj])
        configs = [ServingConfig.from_json(c) for c in raw]
    else:
        configs = [c for c in default_serving_configs() if c.name == spec]
        if not configs:
            print(f"unknown serving config {spec!r}; shipped: "
                  f"{[c.name for c in default_serving_configs()]} "
                  "(or pass a ServingConfig .json file)", file=sys.stderr)
            return 2
    schemas = extract_key_schemas()
    try:
        per_config = {c.name: [list(k) for k in c.program_keys(schemas)]
                      for c in configs}
    except CompileSurfaceError as e:
        print(f"key derivation failed: {e}", file=sys.stderr)
        return 1
    manifest = ProgramManifest.from_configs(configs, schemas=schemas,
                                            name="derived")
    print(json.dumps({
        "configs": [c.to_json() for c in configs],
        "programs": per_config,
        "manifest": manifest.to_json(),
    }, indent=2))
    return 0


def _thread_report(path=None):
    from .threads import analyze_threads, thread_lint_paths

    if path is None:
        return analyze_threads()
    import os

    paths = [path] if os.path.isfile(path) else thread_lint_paths(path)
    # explicit paths are fixture/audit mode: everything is runtime-strict
    return analyze_threads(paths=paths, runtime_modules=("*",))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="Graph lint over the bundled model zoo programs "
                    "(GPT/ResNet train steps, dense+paged decode) plus the "
                    "thread lint over the host runtime source.")
    parser.add_argument("--self-check", action="store_true",
                        help="lint the model zoo AND the framework's own "
                             "threading discipline, exit non-zero on any "
                             "high-severity finding (the default behavior; "
                             "the flag exists for explicit CI wiring)")
    parser.add_argument("--programs", default=None,
                        help="comma-separated subset of zoo programs "
                             "(default: all; implies graph lint only)")
    parser.add_argument("--threads", nargs="?", const="", default=None,
                        metavar="PATH",
                        help="run ONLY the thread lint: over PATH (file or "
                             "directory, strict/runtime severities — the "
                             "seeded-fixture mode) or the installed "
                             "paddle_tpu package when PATH is omitted")
    parser.add_argument("--surface", nargs="?", const="", default=None,
                        metavar="PATH",
                        help="run ONLY the compile-surface lint (ISSUE-13): "
                             "strict fixture mode over PATH (a .py source, "
                             "a configs+manifest .json spec, or a directory "
                             "of either) or the real tree with the builtin "
                             "allowlist when PATH is omitted")
    parser.add_argument("--hbm", nargs="?", const="", default=None,
                        metavar="NAME|PATH",
                        help="run ONLY the HBM residency lint (ISSUE-14): "
                             "the smoke deployment plan's residency table + "
                             "rules (for one shipped serving config when "
                             "NAME is given), or strict fixture mode over a "
                             "DeploymentPlan .json / make_program() .py / "
                             "directory PATH")
    parser.add_argument("--comms", nargs="?", const="", default=None,
                        metavar="NAME|PATH",
                        help="run ONLY the sharding/collective lint "
                             "(ISSUE-20): compile the continuous step "
                             "programs under the serving mesh, print the "
                             "collective inventory + the five comms rules "
                             "(for one step path when NAME is given: "
                             "prefill_chunk, decode_step, verify_step), or "
                             "strict fixture mode over a make_program() .py "
                             "/ comms-surface .json / directory PATH")
    parser.add_argument("--manifest", nargs="?", const="", default=None,
                        metavar="CONFIG",
                        help="print the derived step-program inventory as "
                             "JSON and exit: for every shipped serving "
                             "config (omitted), one of them by name, or a "
                             "ServingConfig .json file")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON object instead of text")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    from .rules import RULES
    from .threads import THREAD_RULES

    if args.list_rules:
        from .comms import COMMS_RULES
        from .compilesurface import SURFACE_RULES
        from .hbm import HBM_RULES

        for rule_id, fn in RULES.items():
            doc = (fn.__doc__ or "").strip().split("\n")[0]
            print(f"{rule_id:18s} {doc}")
        for rule_id, doc in THREAD_RULES.items():
            print(f"{rule_id:18s} [threads] {doc}")
        for rule_id, doc in SURFACE_RULES.items():
            print(f"{rule_id:18s} [surface] {doc.split(chr(10))[0]}")
        for rule_id, doc in HBM_RULES.items():
            print(f"{rule_id:18s} [hbm] {doc.split(chr(10))[0]}")
        for rule_id, doc in COMMS_RULES.items():
            print(f"{rule_id:18s} [comms] {doc.split(chr(10))[0]}")
        return 0

    if args.manifest is not None:
        return _print_manifest(args.manifest or None)

    reports = []
    tables = []
    if args.comms is not None:
        import os

        from .comms import (_STEP_PATHS, analyze_step_comms,
                            comms_fixture_reports, render_comms_table,
                            step_comms_surfaces)

        if args.comms and os.path.exists(args.comms):
            reports.extend(comms_fixture_reports(args.comms))
        else:
            paths = None
            if args.comms:
                if args.comms not in _STEP_PATHS:
                    print(f"unknown step path {args.comms!r}; available: "
                          f"{list(_STEP_PATHS)} (or pass a fixture PATH)",
                          file=sys.stderr)
                    return 2
                paths = (args.comms,)
            surfaces = step_comms_surfaces(paths=paths)
            tables.append(render_comms_table(surfaces))
            reports.append(analyze_step_comms(paths=paths,
                                              _surfaces=surfaces))
    elif args.hbm is not None:
        import os

        from .hbm import (analyze_hbm_plan, hbm_fixture_reports, smoke_plan)

        if args.hbm and os.path.exists(args.hbm):
            reports.extend(hbm_fixture_reports(args.hbm))
        else:
            try:
                plan = smoke_plan(config_name=args.hbm or None)
            except ValueError as e:
                print(str(e), file=sys.stderr)
                return 2
            tables.append(plan.render_table())
            reports.append(analyze_hbm_plan(plan))
    elif args.surface is not None:
        from .compilesurface import (analyze_compile_surface,
                                     surface_fixture_reports)

        if args.surface:
            reports.extend(surface_fixture_reports(args.surface))
        else:
            reports.append(analyze_compile_surface())
    elif args.threads is not None:
        reports.append(_thread_report(args.threads or None))
    else:
        from .zoo import ZOO_PROGRAMS, zoo_reports

        include = None
        if args.programs:
            include = [p.strip() for p in args.programs.split(",")
                       if p.strip()]
            unknown = [p for p in include if p not in ZOO_PROGRAMS]
            if unknown:
                print(f"unknown program(s) {unknown}; available: "
                      f"{sorted(ZOO_PROGRAMS)}", file=sys.stderr)
                return 2
        reports.extend(zoo_reports(include=include))
        if include is None:     # full self-check covers the host runtime too
            reports.append(_thread_report())
            # ... and audits the suppressions themselves: a builtin entry
            # that matched nothing across the whole run is a stale WARN
            from .core import Report
            from .comms import BUILTIN_COMMS_ALLOWLIST
            from .compilesurface import BUILTIN_SURFACE_ALLOWLIST
            from .findings import (BUILTIN_ALLOWLIST,
                                   stale_allowlist_findings)
            from .hbm import BUILTIN_HBM_ALLOWLIST
            from .threads import BUILTIN_THREAD_ALLOWLIST

            stale = stale_allowlist_findings([
                ("graph", BUILTIN_ALLOWLIST),
                ("thread", BUILTIN_THREAD_ALLOWLIST),
                ("surface", BUILTIN_SURFACE_ALLOWLIST),
                ("hbm", BUILTIN_HBM_ALLOWLIST),
                ("comms", BUILTIN_COMMS_ALLOWLIST),
            ])
            reports.append(Report("allowlist.audit", stale, [],
                                  ("allowlist-stale",)))

    high_total = sum(len(r.high()) for r in reports)
    if args.json:
        print(json.dumps({
            "programs": [r.to_dict() for r in reports],
            "high_total": high_total,
            "status": "ok" if high_total == 0 else "lint-high",
        }))
    else:
        for t in tables:
            print(t)
        for r in reports:
            print(r.render())
        print(f"-- {len(reports)} program(s), {high_total} high-severity "
              f"finding(s) -> {'CLEAN' if high_total == 0 else 'FAIL'}")
    return 0 if high_total == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
