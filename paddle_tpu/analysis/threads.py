"""Thread lint: static lock-order + guarded-field analysis over host code.

PR 5's graph lint covers the *traced* programs; this pass covers the code
that LAUNCHES them — the serving batcher, the continuous scheduler's tick
loop, the checkpoint writer thread, the supervisor, the RLock'd KV pool.
It is an AST analysis over the framework's own source in the style of
Eraser's lockset discipline (Savage et al., SOSP 1997) and RacerD's
compositional ownership/guard inference (Blackshear et al., OOPSLA 2018):
no execution, no imports of the analyzed modules, deterministic findings
with file:line provenance.

Rules (catalog in docs/ANALYSIS.md "Thread lint"):

* ``lock-order-cycle`` (high) — a cycle in the interprocedural
  lock-acquisition graph: lock B is (possibly through method calls)
  acquired while A is held on one path and A while B on another. Two
  threads interleaving those paths deadlock.
* ``unguarded-write`` (high in runtime modules, warn elsewhere) — an
  attribute written outside ``__init__`` with an empty lockset, in a class
  that owns threads or locks, where the write either happens ON a worker
  thread (reachable from a ``threading.Thread(target=...)`` root through
  the call graph) or — for lock-owning classes in the runtime modules —
  anywhere (the strict discipline: shared-by-construction state is guarded
  or documented-atomic, full stop). Documented atomics (Queue, Event,
  deque, itertools.count, contextvars, the locks themselves) are exempt;
  mutating method calls (``.append``/``.pop``/``.update`` ...) on non-atomic
  attributes count as writes.
* ``blocking-under-lock`` (high in runtime modules, warn elsewhere) — a
  blocking call (``sleep``, argument-less ``join``/``wait``, ``.result()``,
  ``Queue.get`` without timeout, ``jax.block_until_ready``, file/socket
  I/O) executed, directly or through a resolved method call, while a lock
  is held. Every other thread that touches that lock now waits on the I/O.
* ``raw-clock`` (warn) — a direct ``time.time()``/``time.monotonic()`` call
  inside a class that defines an injectable clock (``self._clock`` /
  ``_now()``): the chaos suite steers those clocks by skewing, so a raw
  read is a test-determinism hole (and ``time.time()`` is not monotonic).
* ``non-daemon-thread`` (high in runtime modules, warn elsewhere) —
  ``threading.Thread(...)`` without ``daemon=True``: a leaked worker hangs
  interpreter shutdown (the conftest thread-leak guard is the runtime twin).

Known limitations (by design — this is a linter, not a verifier): reads are
not raced against writes (write-side discipline only), dataflow through
containers/locals is not tracked, and cross-class calls resolve only when
the method name is unique among analyzed classes (ambiguity skips, never
guesses). The runtime lock witness (``analysis/lockwitness.py``) covers the
dynamic side the static pass cannot see.
"""
from __future__ import annotations

import ast
import fnmatch
import os

from .findings import HIGH, INFO, WARN, Allowlist, AllowlistEntry, Finding

__all__ = ["THREAD_RULES", "RUNTIME_MODULES", "BUILTIN_THREAD_ALLOWLIST",
           "analyze_threads", "lock_order_graph", "record_findings",
           "thread_lint_paths"]

THREAD_RULES = {
    "lock-order-cycle": "cycle in the interprocedural lock-acquisition "
                        "graph (potential deadlock)",
    "unguarded-write": "shared attribute written without holding a lock "
                       "(and not a documented atomic)",
    "blocking-under-lock": "blocking call (sleep/join/result/Queue.get/"
                           "I/O) while holding a lock",
    "raw-clock": "raw time.time()/time.monotonic() in a class with an "
                 "injectable clock",
    "non-daemon-thread": "threading.Thread(...) without daemon=True in "
                         "runtime code",
}

#: The threaded host-runtime modules where the strict discipline is
#: mandatory (findings are high severity here, warn elsewhere). Matched as
#: path suffixes against the analyzed file's os-normalized path.
RUNTIME_MODULES = (
    "inference/serving.py",
    "inference/scheduler.py",
    "inference/kv_cache.py",
    "inference/prefix_cache.py",
    "inference/adapters.py",
    "inference/qos.py",
    "inference/resilience.py",
    "inference/faults.py",
    "framework/checkpoint.py",
)

# constructors whose instances are documented-atomic under the GIL /
# internally locked — attributes holding them are exempt from the
# unguarded-write rule
_ATOMIC_CTORS = {
    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue", "Event",
    "Semaphore", "BoundedSemaphore", "Barrier", "local", "ContextVar",
    "count", "deque",
}
_LOCK_CTORS = {"Lock", "RLock", "Condition", "make_lock", "make_rlock"}

# method names that mutate their receiver in place — a call
# ``self.attr.append(...)`` is a WRITE to ``attr`` for the guard rule
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft", "popitem",
    "remove", "clear", "update", "add", "discard", "setdefault", "sort",
}

_QUEUEISH = ("queue", "_q")     # base-attr name hints for Queue.get


def _is_queueish(name: str) -> bool:
    n = name.lower()
    return n in ("q", "_q") or "queue" in n


class _MethodInfo:
    __slots__ = ("cls", "name", "lineno",
                 "writes",       # [(attr, lockset, lineno, kind)]
                 "reads",        # {attr: {"locked": bool, "unlocked": bool}}
                 "calls",        # [(kind, name, lockset, lineno)]
                 "acquires",     # [(canonical_lock, lockset, lineno)]
                 "blocking",     # [(desc, lockset, lineno)]
                 "rawclock",     # [(expr, lineno)]
                 "threads",      # [(target_attr|None, daemon_ok, lineno)]
                 "acq_summary", "blk_summary")

    def __init__(self, cls, name, lineno):
        self.cls = cls
        self.name = name
        self.lineno = lineno
        self.writes = []
        self.reads = {}
        self.calls = []
        self.acquires = []
        self.blocking = []
        self.rawclock = []
        self.threads = []
        self.acq_summary = None
        self.blk_summary = None

    @property
    def qualname(self):
        return f"{self.cls.qualname}.{self.name}"


class _ClassInfo:
    __slots__ = ("module", "name", "path", "bases", "methods", "lock_attrs",
                 "atomic_attrs", "has_clock", "runtime")

    def __init__(self, module, name, path, bases, runtime):
        self.module = module        # module basename without .py
        self.name = name
        self.path = path            # repo-relative display path
        self.bases = bases          # base-class simple names
        self.methods = {}           # name -> _MethodInfo
        self.lock_attrs = set()
        self.atomic_attrs = set()
        self.has_clock = False
        self.runtime = runtime

    @property
    def qualname(self):
        return f"{self.module}.{self.name}"


# --------------------------------------------------------------- AST helpers
def _self_attr(node):
    """'attr' when node is ``self.attr``, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _ctor_name(call):
    """Simple constructor name of a Call: Queue() / queue.Queue() -> Queue."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _is_thread_ctor(call):
    f = call.func
    if isinstance(f, ast.Name) and f.id == "Thread":
        return True
    return isinstance(f, ast.Attribute) and f.attr == "Thread"


def _kwarg(call, name):
    for kw in call.keywords:
        if kw.arg == name:
            return kw
    return None


def _blocking_desc(call):
    """Why this Call blocks, or None."""
    f = call.func
    if isinstance(f, ast.Name):
        if f.id == "open":
            return "file open()"
        if f.id in ("sleep",):
            return "sleep()"
        return None
    if not isinstance(f, ast.Attribute):
        return None
    a = f.attr
    if a in ("sleep", "_sleep"):
        return "sleep"
    if a == "result":
        return ".result() on a future"
    if a == "block_until_ready":
        return "jax.block_until_ready (device sync)"
    if a in ("recv", "accept", "connect", "select", "urlopen"):
        return f"socket/net .{a}()"
    if a == "join" and not call.args and not call.keywords:
        return ".join() without timeout"
    if a == "wait" and not call.args and not call.keywords:
        return ".wait() without timeout"
    if a == "get" and _kwarg(call, "timeout") is None and not call.args:
        base = f.value
        bname = (_self_attr(base) or
                 (base.id if isinstance(base, ast.Name) else
                  base.attr if isinstance(base, ast.Attribute) else ""))
        if bname and _is_queueish(bname):
            return "Queue.get() without timeout"
    return None


def _is_raw_clock(call):
    f = call.func
    return (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            and f.value.id == "time" and f.attr in ("time", "monotonic"))


class _MethodWalker:
    """Walks one method body tracking the held lockset through ``with``."""

    def __init__(self, cls: _ClassInfo, meth: _MethodInfo):
        self.cls = cls
        self.meth = meth

    def canon(self, attr):
        return f"{self.cls.qualname}.{attr}"

    def walk(self, stmts, held: frozenset):
        for st in stmts:
            self.stmt(st, held)

    def stmt(self, st, held):
        if isinstance(st, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in st.items:
                self.expr(item.context_expr, held)
                attr = _self_attr(item.context_expr)
                if attr is not None and attr in self.cls.lock_attrs:
                    name = self.canon(attr)
                    self.meth.acquires.append((name, held, st.lineno))
                    acquired.append(name)
            inner = held.union(acquired) if acquired else held
            self.walk(st.body, inner)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested closure: approximate with the def-site lockset (the
            # common pattern here is a helper called within the same block)
            self.walk(st.body, held)
        elif isinstance(st, ast.ClassDef):
            pass
        elif isinstance(st, (ast.If, ast.While)):
            self.expr(st.test, held)
            self.walk(st.body, held)
            self.walk(st.orelse, held)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self.expr(st.iter, held)
            self.expr(st.target, held)
            self.walk(st.body, held)
            self.walk(st.orelse, held)
        elif isinstance(st, (ast.Try,)):
            self.walk(st.body, held)
            for h in st.handlers:
                self.walk(h.body, held)
            self.walk(st.orelse, held)
            self.walk(st.finalbody, held)
        elif isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (st.targets if isinstance(st, ast.Assign)
                       else [st.target])
            for t in targets:
                self.write_target(t, held, st.lineno,
                                  aug=isinstance(st, ast.AugAssign))
            value = getattr(st, "value", None)
            if value is not None:
                self.expr(value, held)
            if isinstance(st, ast.AugAssign):   # aug target is also a read
                self.expr(st.target, held, store_ok=True)
        else:
            self.expr_stmt(st, held)

    def write_target(self, t, held, lineno, aug=False):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self.write_target(e, held, lineno, aug=aug)
            return
        attr = _self_attr(t)
        if attr is not None:
            self.meth.writes.append((attr, held, lineno, "assign"))
            return
        if isinstance(t, ast.Subscript):
            attr = _self_attr(t.value)
            if attr is not None:
                self.meth.writes.append((attr, held, lineno, "subscript"))
            else:
                self.expr(t.value, held)
            self.expr(t.slice, held)
        elif isinstance(t, (ast.Attribute,)):
            self.expr(t.value, held)    # obj.attr = ...: record obj read

    def expr_stmt(self, st, held):
        for node in ast.iter_child_nodes(st):
            if isinstance(node, ast.expr):
                self.expr(node, held)

    # ------------------------------------------------------------ expressions
    def expr(self, node, held, store_ok=False):
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                self.call(n, held)
            elif isinstance(n, ast.Attribute):
                attr = _self_attr(n)
                if attr is not None and (isinstance(n.ctx, ast.Load)
                                         or store_ok):
                    st = self.meth.reads.setdefault(
                        attr, {"locked": False, "unlocked": False})
                    st["locked" if held else "unlocked"] = True

    def call(self, call, held):
        f = call.func
        # thread construction (daemon rule + roots)
        if _is_thread_ctor(call):
            target = _kwarg(call, "target")
            troot = _self_attr(target.value) if target is not None else None
            dkw = _kwarg(call, "daemon")
            daemon_ok = dkw is not None and not (
                isinstance(dkw.value, ast.Constant) and dkw.value.value is False)
            self.meth.threads.append((troot, daemon_ok, call.lineno))
        desc = _blocking_desc(call)
        if desc is not None:
            self.meth.blocking.append((desc, held, call.lineno))
        if _is_raw_clock(call):
            self.meth.rawclock.append((f"time.{f.attr}()", call.lineno))
        # mutating method call on a self attribute counts as a write
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            attr = _self_attr(f.value)
            if attr is not None:
                self.meth.writes.append((attr, held, call.lineno, "mutate"))
        # call-graph edges
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                self.meth.calls.append(("self", f.attr, held, call.lineno))
            else:
                self.meth.calls.append(("ext", f.attr, held, call.lineno))


# --------------------------------------------------------------- collection
def thread_lint_paths(root=None):
    """Default file set: every .py under the paddle_tpu package."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


def _is_runtime(relpath, runtime_modules):
    rp = relpath.replace(os.sep, "/")
    return any(rp.endswith(m) or fnmatch.fnmatch(rp, m)
               for m in runtime_modules)


class _Model:
    """Parsed view of the analyzed file set."""

    def __init__(self):
        self.classes = []               # [_ClassInfo]
        self.by_name = {}               # class simple name -> [_ClassInfo]
        self.methods_by_name = {}       # method name -> [_MethodInfo]
        self.module_threads = []        # [(relpath, runtime, daemon_ok, ln)]
        self.parse_errors = []          # [(relpath, error)]

    def add_class(self, ci):
        self.classes.append(ci)
        self.by_name.setdefault(ci.name, []).append(ci)
        for m in ci.methods.values():
            self.methods_by_name.setdefault(m.name, []).append(m)

    # --------------------------------------------------------- resolution
    def mro(self, ci):
        """Syntactic MRO approximation: the class then its bases depth-first
        (unique-name lookup; ambiguous or unknown bases stop the chain)."""
        out, seen, work = [], set(), [ci]
        while work:
            c = work.pop(0)
            if c.qualname in seen:
                continue
            seen.add(c.qualname)
            out.append(c)
            for b in c.bases:
                cands = self.by_name.get(b, [])
                if len(cands) == 1:
                    work.append(cands[0])
        return out

    def effective(self, ci):
        """name -> _MethodInfo honoring overrides (nearest in MRO wins)."""
        table = {}
        for c in self.mro(ci):
            for name, m in c.methods.items():
                table.setdefault(name, m)
        return table

    def lock_attrs(self, ci):
        return set().union(*(c.lock_attrs for c in self.mro(ci)))

    def atomic_attrs(self, ci):
        return set().union(*(c.atomic_attrs for c in self.mro(ci)))

    def has_clock(self, ci):
        return any(c.has_clock for c in self.mro(ci))

    def resolve_call(self, caller_cls, kind, name):
        """Best-effort callee resolution: self-calls in the caller's MRO,
        then (for both kinds) globally when the method name is unique."""
        if kind == "self":
            table = self.effective(caller_cls)
            if name in table:
                return table[name]
        cands = self.methods_by_name.get(name, [])
        if len(cands) == 1:
            return cands[0]
        return None


def _parse(paths, runtime_modules):
    model = _Model()
    common = os.path.commonpath([os.path.abspath(p) for p in paths]) \
        if len(paths) > 1 else os.path.dirname(os.path.abspath(paths[0]))
    for path in paths:
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, common)
        runtime = _is_runtime(ap, runtime_modules)
        try:
            with open(ap, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=ap)
        except (OSError, SyntaxError) as e:
            model.parse_errors.append((rel, repr(e)))
            continue
        modname = os.path.splitext(os.path.basename(ap))[0]
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                _collect_class(model, node, modname, rel, runtime)
            else:
                # module-level / free-function Thread ctors (daemon rule);
                # class bodies are covered by the per-method walk
                for n in ast.walk(node):
                    if isinstance(n, ast.Call) and _is_thread_ctor(n):
                        dkw = _kwarg(n, "daemon")
                        ok = dkw is not None and not (
                            isinstance(dkw.value, ast.Constant)
                            and dkw.value.value is False)
                        model.module_threads.append((rel, runtime, ok,
                                                     n.lineno))
    return model


def _collect_class(model, node, modname, rel, runtime):
    bases = []
    for b in node.bases:
        if isinstance(b, ast.Name):
            bases.append(b.id)
        elif isinstance(b, ast.Attribute):
            bases.append(b.attr)
    ci = _ClassInfo(modname, node.name, rel, bases, runtime)
    # first sweep: lock/atomic attribute classification + injectable clock
    for item in node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if item.name in ("_now",):
            ci.has_clock = True
        for n in ast.walk(item):
            if not isinstance(n, (ast.Assign, ast.AnnAssign)):
                continue
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            if isinstance(n.value, ast.Call):
                ctor = _ctor_name(n.value)
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if ctor in _LOCK_CTORS:
                        ci.lock_attrs.add(attr)
                    elif ctor in _ATOMIC_CTORS:
                        ci.atomic_attrs.add(attr)
            for t in targets:
                if _self_attr(t) in ("_clock", "clock"):
                    ci.has_clock = True
    # second sweep: per-method walk
    for item in node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        meth = _MethodInfo(ci, item.name, item.lineno)
        _MethodWalker(ci, meth).walk(item.body, frozenset())
        ci.methods[item.name] = meth
    model.add_class(ci)


# ----------------------------------------------------- interprocedural passes
_MAX_SUMMARY = 64


def _summaries(model):
    """Fixed-point acquire/blocking summaries per method.

    acq_summary: {(lock, heldset_within_callee_frame)}; blk_summary:
    {(desc, heldset)} — call sites lift callee entries by their own held
    set, so 'sleep under a lock three calls down' still lands on the
    outermost holder."""
    methods = [m for ms in model.methods_by_name.values() for m in ms]
    for m in methods:
        m.acq_summary = {(lk, held) for lk, held, _ in m.acquires}
        m.blk_summary = {(d, held) for d, held, _ in m.blocking}
    for _ in range(6):      # call-chain depth cap; graphs here are shallow
        changed = False
        for m in methods:
            for kind, name, held, _ln in m.calls:
                callee = model.resolve_call(m.cls, kind, name)
                if callee is None or callee is m:
                    continue
                for lk, h in list(callee.acq_summary)[:_MAX_SUMMARY]:
                    e = (lk, held | h)
                    if e not in m.acq_summary and len(m.acq_summary) < _MAX_SUMMARY:
                        m.acq_summary.add(e)
                        changed = True
                for d, h in list(callee.blk_summary)[:_MAX_SUMMARY]:
                    # tag the blocking origin so a finding three calls up
                    # still names the method that actually blocks (and the
                    # allowlist can match on it)
                    if "(in " not in d:
                        d = f"{d} (in {callee.qualname})"
                    e = (d, held | h)
                    if e not in m.blk_summary and len(m.blk_summary) < _MAX_SUMMARY:
                        m.blk_summary.add(e)
                        changed = True
        if not changed:
            break


def _thread_roots(model):
    """(class, _MethodInfo) thread-entry points, resolved per concrete
    class so subclass overrides of a base's worker loop are reachable."""
    roots = []
    for ci in model.classes:
        table = model.effective(ci)
        for m in table.values():
            for target, _ok, _ln in m.threads:
                if target is not None and target in table:
                    roots.append((ci, table[target]))
    return roots


def _reachable(model):
    """Methods reachable from any thread root through resolved calls.
    Walked per (method, concrete-class) context so a subclass's override of
    a base's worker loop is reached through the inherited thread root."""
    seen_ctx, reachable = set(), set()
    work = [(m, ci) for ci, m in _thread_roots(model)]
    while work:
        m, ctx = work.pop()
        key = (id(m), ctx.qualname)
        if key in seen_ctx:
            continue
        seen_ctx.add(key)
        reachable.add(id(m))
        for kind, name, _held, _ln in m.calls:
            callee = model.resolve_call(ctx, kind, name)
            if callee is None:
                continue
            # self-calls stay in the concrete class's context (overrides
            # resolve there); ext-calls switch to the callee's own class
            nctx = ctx if kind == "self" else callee.cls
            work.append((callee, nctx))
    return reachable


def _class_has_roots(model, ci):
    table = model.effective(ci)
    return any(t is not None and t in table
               for m in table.values() for t, _ok, _ln in m.threads)


# ------------------------------------------------------------- lock graph
def _lock_edges(model):
    """{(held_lock, acquired_lock): 'path:line (Class.method)'} over the
    whole file set, interprocedural."""
    edges = {}
    for ms in model.methods_by_name.values():
        for m in ms:
            for lk, held in m.acq_summary:
                for h in held:
                    if h != lk and (h, lk) not in edges:
                        site = f"{m.cls.path} ({m.qualname})"
                        edges[(h, lk)] = site
    return edges


def _cycles(edges):
    from .lockwitness import _find_cycles

    adj = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    return _find_cycles(adj)


def lock_order_graph(root=None, paths=None, runtime_modules=RUNTIME_MODULES):
    """The statically-inferred lock-acquisition order: {(held, acquired):
    site}. The runtime witness checks its observed order against this
    (``LockWitness.check_static``)."""
    paths = paths if paths is not None else thread_lint_paths(root)
    model = _parse(paths, runtime_modules)
    _summaries(model)
    return _lock_edges(model)


# ------------------------------------------------------------ rule emission
def _sev(runtime):
    return HIGH if runtime else WARN


def _guarded_elsewhere(model, ci, attr):
    for c in model.mro(ci):
        for m in c.methods.values():
            st = m.reads.get(attr)
            if st and st["locked"]:
                return True
            for a, held, _ln, _k in m.writes:
                if a == attr and held:
                    return True
    return False


def _emit_findings(model):
    findings = []

    # lock-order-cycle --------------------------------------------------
    edges = _lock_edges(model)
    for cyc in _cycles(edges):
        path = " -> ".join(cyc)
        sites = []
        for a, b in zip(cyc, cyc[1:]):
            if (a, b) in edges:
                sites.append(f"{a}->{b} @ {edges[(a, b)]}")
        findings.append(Finding(
            "lock-order-cycle", HIGH,
            f"lock acquisition cycle {path}: two threads interleaving "
            f"these paths deadlock ({'; '.join(sites[:3])})",
            remediation="impose one global acquisition order (acquire the "
                        "cycle's locks in a fixed order everywhere), or "
                        "narrow one side to not call out while holding"))

    reachable = _reachable(model)

    for ci in model.classes:
        lock_attrs = model.lock_attrs(ci)
        atomic_attrs = model.atomic_attrs(ci)
        has_roots = _class_has_roots(model, ci)
        eligible = bool(lock_attrs) or has_roots
        for m in ci.methods.values():
            where = f"{ci.path}:{{ln}} ({m.qualname})"

            # unguarded-write -------------------------------------------
            if eligible and m.name != "__init__":
                on_thread = id(m) in reachable
                for attr, held, ln, kind in m.writes:
                    if held or attr in lock_attrs or attr in atomic_attrs:
                        continue
                    if attr.startswith("__"):
                        continue
                    strict = ci.runtime and bool(lock_attrs)
                    if not (on_thread or strict):
                        continue
                    why = ("written on a worker thread" if on_thread
                           else "written in a lock-owning runtime class")
                    extra = (" (the attribute IS guarded elsewhere — "
                             "inconsistent lockset)"
                             if _guarded_elsewhere(model, ci, attr) else "")
                    verb = ("mutated in place" if kind == "mutate"
                            else "written")
                    findings.append(Finding(
                        "unguarded-write", _sev(ci.runtime),
                        f"{ci.qualname}.{attr} {verb} with no lock held — "
                        f"{why}{extra}",
                        where=where.format(ln=ln),
                        remediation="hold the class lock around the write, "
                                    "use a documented atomic (Queue/Event/"
                                    "deque/itertools.count), or allowlist "
                                    "with the reason the race is benign"))

            # blocking-under-lock ---------------------------------------
            for desc, held, ln in m.blocking:
                if held:
                    findings.append(Finding(
                        "blocking-under-lock", _sev(ci.runtime),
                        f"{m.qualname} blocks ({desc}) while holding "
                        f"{', '.join(sorted(held))}",
                        where=where.format(ln=ln),
                        remediation="move the blocking call outside the "
                                    "critical section (copy state under "
                                    "the lock, block after release)"))
            # ... including through resolved calls (one finding per site)
            for kind, name, held, ln in m.calls:
                if not held:
                    continue
                callee = model.resolve_call(ci, kind, name)
                if callee is None:
                    continue
                blk = [d for d, h in callee.blk_summary]
                if blk:
                    findings.append(Finding(
                        "blocking-under-lock", _sev(ci.runtime),
                        f"{m.qualname} calls {callee.qualname} (which may "
                        f"block: {blk[0]}) while holding "
                        f"{', '.join(sorted(held))}",
                        where=where.format(ln=ln),
                        remediation="move the call outside the critical "
                                    "section or make the callee "
                                    "non-blocking"))

            # raw-clock --------------------------------------------------
            # the clock-defining method itself (the `else time.monotonic`
            # fallback in _now/monotonic) IS the injectable read-through
            if model.has_clock(ci) and m.name not in ("_now", "monotonic",
                                                      "_clock"):
                for expr, ln in m.rawclock:
                    findings.append(Finding(
                        "raw-clock", WARN,
                        f"{m.qualname} reads {expr} directly but the class "
                        f"has an injectable clock — skew-driven chaos tests "
                        f"cannot steer this timing",
                        where=where.format(ln=ln),
                        remediation="read through self._clock()/self._now() "
                                    "(the injector's skewable clock)"))

            # non-daemon-thread ------------------------------------------
            for _target, daemon_ok, ln in m.threads:
                if not daemon_ok:
                    findings.append(Finding(
                        "non-daemon-thread", _sev(ci.runtime),
                        f"{m.qualname} starts a Thread without daemon=True "
                        f"— a leaked worker hangs interpreter shutdown",
                        where=where.format(ln=ln),
                        remediation="pass daemon=True (and join explicitly "
                                    "on clean shutdown)"))

    # module-level Thread ctors outside class methods -------------------
    for rel, runtime, ok, ln in model.module_threads:
        if not ok:
            findings.append(Finding(
                "non-daemon-thread", _sev(runtime),
                "threading.Thread(...) without daemon=True",
                where=f"{rel}:{ln}",
                remediation="pass daemon=True"))

    for rel, err in model.parse_errors:
        findings.append(Finding(
            "rule-error", INFO, f"{rel} failed to parse: {err}"[:300]))
    return findings


# ----------------------------------------------------------------- allowlist
#: Intentional, justified exceptions on the repo's own tree. Every entry is
#: a finding the analyzer is RIGHT about but the code is right to keep —
#: suppressions stay visible in Report.suppressed.
BUILTIN_THREAD_ALLOWLIST = Allowlist([
    AllowlistEntry(
        "unguarded-write", subject="thread-lint", contains="._busy",
        reason="single-writer worker-liveness flag: only the batcher thread "
               "writes it, readers (pending()/drain polls) tolerate a stale "
               "bool, and CPython guarantees torn-free bool stores"),
    AllowlistEntry(
        "blocking-under-lock", subject="thread-lint",
        contains="Supervisor.heal",
        reason="heal() sleeps its restart backoff under the supervisor lock "
               "BY DESIGN: the lock serializes concurrent healers so exactly "
               "one client pays the backoff and restarts the worker"),
    # (a FaultInjector.check blocking-under-lock entry lived here until the
    # ISSUE-14 stale-suppression audit flagged it: the instrumented sleep
    # site it excused no longer lints as blocking, so the entry was dead
    # weight — exactly the rot allowlist-stale exists to catch)
    AllowlistEntry(
        "blocking-under-lock", subject="thread-lint", contains="TCPStore",
        reason="the store lock serializes the single-socket request/response "
               "protocol — a blocking read under it IS the framing contract "
               "(two interleaved writers would corrupt the wire format)"),
    AllowlistEntry(
        "unguarded-write", subject="thread-lint", contains="._last_launch",
        reason="tick-thread-only stash: the launch-timing hook writes it and "
               "the utilization tick fns read it back on the SAME scheduler "
               "loop thread within one launch — no second thread ever "
               "touches it, and taking _slot_lock inside the timing hook "
               "would risk lock re-entry from launch paths"),
    AllowlistEntry(
        "unguarded-write", subject="thread-lint",
        contains="InferenceServer.profile_dir",
        reason="lazy tmpdir resolution runs only while self._profile_lock "
               "is held: the /debug/profile handler acquires it "
               "non-blockingly (single-flight, 409 otherwise) before "
               "calling _capture_profile, so writers are serialized — the "
               "lint can't see the caller-held lock"),
    AllowlistEntry(
        "raw-clock", subject="thread-lint",
        contains="CheckpointManager._commit reads time.time()",
        reason="the manifest's wall_time stamp is informational only; "
               "checkpoint discovery orders by step number, never by clock "
               "(clock skew cannot resurrect old state)"),
])


# --------------------------------------------------------------- entry point
def analyze_threads(root=None, paths=None, *, runtime_modules=None,
                    allowlist=None, name="thread-lint",
                    max_findings_per_rule=32):
    """Run the thread lint over a file set (default: the whole installed
    ``paddle_tpu`` package) and return a ``Report``.

    ``runtime_modules`` — path suffixes/globs where the strict discipline is
    high severity (default :data:`RUNTIME_MODULES`; pass ``("*",)`` to treat
    everything as runtime, e.g. for seeded-violation fixtures).
    ``allowlist`` defaults to :data:`BUILTIN_THREAD_ALLOWLIST`; suppressions
    require a reason and stay visible in ``Report.suppressed``."""
    from .core import Report

    runtime_modules = (RUNTIME_MODULES if runtime_modules is None
                       else tuple(runtime_modules))
    paths = paths if paths is not None else thread_lint_paths(root)
    if not paths:
        return Report(name, [], [], tuple(THREAD_RULES))
    model = _parse(paths, runtime_modules)
    _summaries(model)
    findings = _emit_findings(model)
    # deterministic order + per-rule cap
    order = {HIGH: 0, WARN: 1, INFO: 2}
    findings.sort(key=lambda f: (f.rule, order.get(f.severity, 3), f.where))
    capped, counts = [], {}
    for f in findings:
        n = counts.get(f.rule, 0)
        if n == max_findings_per_rule:
            capped.append(Finding(
                f.rule, f.severity,
                f"... further {f.rule} findings truncated "
                f"(cap {max_findings_per_rule})"))
        if n >= max_findings_per_rule:
            counts[f.rule] = n + 1
            continue
        counts[f.rule] = n + 1
        capped.append(f)
    for f in capped:
        f.subject = f.subject or name
    if allowlist is None:
        allowlist = BUILTIN_THREAD_ALLOWLIST
    kept, suppressed = allowlist.apply(capped, backend="")
    return Report(name, kept, suppressed, tuple(THREAD_RULES))


def record_findings(report, registry):
    """Count a report's findings (kept + suppressed) into
    ``paddle_analysis_findings_total{rule,severity}`` on a
    ``observability.metrics.MetricsRegistry`` — the same series StepMonitor
    feeds for graph lint, so thread-rule series ride the existing scrape."""
    counter = registry.counter(
        "paddle_analysis_findings_total",
        "Static-analysis findings by rule and severity",
        labels=("rule", "severity"))
    for f in report.findings:
        counter.labels(f.rule, f.severity).inc()
    for f, _e in report.suppressed:
        counter.labels(f.rule, "suppressed").inc()
    return counter
