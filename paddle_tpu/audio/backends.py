"""Audio IO backends. Reference: python/paddle/audio/backends/
(init_backend.py registry + wave_backend.py stdlib-wave PCM16 io).

Only the 'wave' backend ships (the reference's default without paddleaudio
installed — wave_backend.py:95); the registry mirrors the reference so
`set_backend('soundfile')` fails the same way it does there without the
optional package.
"""
from __future__ import annotations

import wave

import numpy as np


class AudioInfo:
    """Reference: backends/backend.py AudioInfo."""

    def __init__(self, sample_rate, num_samples, num_channels, bits_per_sample,
                 encoding):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


_BACKEND = "wave"


def list_available_backends():
    """Reference init_backend.py:38 — paddleaudio isn't shipped, so: wave."""
    return ["wave"]


def get_current_backend():
    return _BACKEND


def set_backend(backend_name):
    """Reference init_backend.py:140."""
    global _BACKEND
    if backend_name not in list_available_backends():
        raise NotImplementedError(
            f"backend {backend_name!r} unavailable — only {list_available_backends()} "
            "ship here (the reference gets more via the optional paddleaudio wheel)")
    _BACKEND = backend_name


def info(filepath):
    """Reference wave_backend.py:43 — PCM16 WAV header info."""
    with wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8, "PCM_S")


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Reference wave_backend.py:95 — PCM16 WAV only; normalize → float32 in
    (-1, 1); returns (Tensor [C, T] if channels_first, sample_rate)."""
    from ..tensor import Tensor

    file_obj = filepath if hasattr(filepath, "read") else open(filepath, "rb")
    try:
        f = wave.open(file_obj)
    except wave.Error:
        file_obj.close()
        raise NotImplementedError(
            "only PCM16 WAV is supported by the wave backend")
    channels = f.getnchannels()
    sample_rate = f.getframerate()
    frames = f.getnframes()
    sampwidth = f.getsampwidth()
    content = f.readframes(frames)
    file_obj.close()
    if sampwidth != 2:
        raise NotImplementedError(
            f"only PCM16 WAV is supported by the wave backend "
            f"(got {8 * sampwidth}-bit)")

    audio = np.frombuffer(content, dtype=np.int16).astype(np.float32)
    if normalize:
        audio = audio / 2.0 ** 15
    waveform = np.reshape(audio, (frames, channels))
    end = None if num_frames == -1 else frame_offset + num_frames
    waveform = waveform[frame_offset:end, :]
    if channels_first:
        waveform = waveform.T
    import jax.numpy as jnp

    return Tensor(jnp.asarray(waveform)), sample_rate


def save(filepath, src, sample_rate, channels_first=True, encoding="PCM_S",
         bits_per_sample=16):
    """Reference wave_backend.py:174 — float (-1,1) [C,T] → PCM16 WAV."""
    from ..tensor import Tensor

    data = np.asarray(src._value if isinstance(src, Tensor) else src)
    if data.ndim == 1:
        data = data[:, None]  # mono → (T, 1) regardless of channels_first
    elif channels_first:
        data = data.T  # → (T, C)
    if bits_per_sample != 16:
        raise ValueError("wave backend writes PCM16 only")
    pcm = np.clip(data, -1.0, 1.0)
    pcm = (pcm * (2 ** 15 - 1)).astype("<i2")
    with wave.open(filepath, "wb") as f:
        f.setnchannels(data.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(pcm.tobytes())
