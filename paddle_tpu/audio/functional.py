"""paddle.audio.functional — DSP building blocks.

Reference: python/paddle/audio/functional/functional.py:29 (hz_to_mel),
:83 (mel_to_hz), :189 (compute_fbank_matrix), :262 (power_to_db), :306
(create_dct), window functions in window.py. All math is jnp (XLA-compiled on
TPU); spectrogram hot paths use paddle_tpu.fft (XLA FFT).
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor


def _val(x):
    return x._value if isinstance(x, Tensor) else x


def hz_to_mel(freq, htk=False):
    scalar = not hasattr(freq, "shape") or getattr(freq, "ndim", 0) == 0
    f = jnp.asarray(_val(freq), jnp.float32)
    if htk:
        out = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = jnp.where(f >= min_log_hz,
                        min_log_mel + jnp.log(jnp.maximum(f, 1e-10) / min_log_hz)
                        / logstep, mels)
    return float(out) if scalar and not isinstance(freq, Tensor) else Tensor(out)


def mel_to_hz(mel, htk=False):
    scalar = not hasattr(mel, "shape") or getattr(mel, "ndim", 0) == 0
    m = jnp.asarray(_val(mel), jnp.float32)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = jnp.where(m >= min_log_mel,
                        min_log_hz * jnp.exp(logstep * (m - min_log_mel)), freqs)
    return float(out) if scalar and not isinstance(mel, Tensor) else Tensor(out)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False, dtype="float32"):
    low = _val(hz_to_mel(f_min, htk))
    high = _val(hz_to_mel(f_max, htk))
    low = float(low) if not isinstance(low, float) else low
    high = float(high) if not isinstance(high, float) else high
    mels = jnp.linspace(low, high, n_mels)
    return Tensor(_val(mel_to_hz(Tensor(mels), htk)).astype(dtype))


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor(jnp.linspace(0, sr / 2, n_fft // 2 + 1).astype(dtype))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None, htk=False,
                         norm="slaney", dtype="float32"):
    """[n_mels, n_fft//2 + 1] mel filterbank (reference functional.py:189)."""
    if f_max is None:
        f_max = sr / 2.0
    fftfreqs = _val(fft_frequencies(sr, n_fft))
    melfreqs = _val(mel_frequencies(n_mels + 2, f_min, f_max, htk))
    fdiff = jnp.diff(melfreqs)
    ramps = melfreqs[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melfreqs[2:n_mels + 2] - melfreqs[:n_mels])
        weights = weights * enorm[:, None]
    return Tensor(weights.astype(dtype))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """10*log10(S/ref) clipped at top_db below the peak (reference :262)."""
    s = jnp.asarray(_val(spect))
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
    log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(amin, ref_value))
    if top_db is not None:
        if top_db < 0:
            raise ValueError("top_db must be non-negative")
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return Tensor(log_spec)


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """[n_mels, n_mfcc] DCT-II basis (reference :306)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[None, :]
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm is None:
        dct = dct * 2.0
    else:
        if norm != "ortho":
            raise ValueError("norm must be 'ortho' or None")
        dct = dct * jnp.where(k == 0, math.sqrt(1.0 / (4 * n_mels)),
                              math.sqrt(1.0 / (2 * n_mels))) * 2.0
    return Tensor(dct.astype(dtype))


# ------------------------------------------------------------------ windows
def get_window(window, win_length, fftbins=True, dtype="float32"):
    """hann/hamming/blackman/bartlett/kaiser(+beta)/gaussian(+std) windows."""
    if isinstance(window, (tuple, list)):
        name, *params = window
    else:
        name, params = window, []
    n = win_length
    # periodic (fftbins) windows divide by N, symmetric by N-1
    denom = n if fftbins else max(n - 1, 1)
    i = np.arange(n)
    if name == "hann":
        w = 0.5 - 0.5 * np.cos(2 * np.pi * i / denom)
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * i / denom)
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * i / denom)
             + 0.08 * np.cos(4 * np.pi * i / denom))
    elif name == "bartlett":
        w = 1.0 - np.abs(2.0 * i / denom - 1.0)
    elif name == "kaiser":
        beta = params[0] if params else 12.0
        w = np.kaiser(n + (1 if fftbins else 0), beta)[:n]
    elif name == "gaussian":
        std = params[0] if params else 7.0
        w = np.exp(-0.5 * ((i - (n - 1) / 2) / std) ** 2)
    elif name in ("rect", "boxcar", "ones"):
        w = np.ones(n)
    else:
        raise ValueError(f"unknown window {name!r}")
    return Tensor(jnp.asarray(w.astype(dtype)))
