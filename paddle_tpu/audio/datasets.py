"""Audio classification datasets. Reference: python/paddle/audio/datasets/
(dataset.py AudioClassificationDataset, esc50.py ESC50, tess.py TESS).

Zero-egress policy (same as vision/datasets): the archive is never fetched;
point `data_home` (or the PADDLE_TPU_DATA_HOME env var) at an
already-downloaded extraction. Layouts expected:
  ESC50: <data_home>/ESC-50-master/{meta/esc50.csv, audio/*.wav}
  TESS:  <data_home>/TESS_Toronto_emotional_speech_set/**/<spk>_<word>_<emotion>.wav
"""
from __future__ import annotations

import os

from ..io import Dataset
from . import backends
from .features import MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram

feat_funcs = {
    "raw": None,
    "melspectrogram": MelSpectrogram,
    "mfcc": MFCC,
    "logmelspectrogram": LogMelSpectrogram,
    "spectrogram": Spectrogram,
}


def _data_home(data_home):
    home = data_home or os.environ.get("PADDLE_TPU_DATA_HOME")
    if home is None:
        raise RuntimeError(
            "no network egress: download is disabled. Pass data_home= (or set "
            "PADDLE_TPU_DATA_HOME) to the directory holding the extracted "
            "archive — see paddle_tpu/audio/datasets.py docstring for layout")
    return home


class AudioClassificationDataset(Dataset):
    """Reference datasets/dataset.py:30 — (feature, label) pairs over wav
    files, with the feature extractor chosen by feat_type."""

    def __init__(self, files, labels, feat_type="raw", sample_rate=None,
                 **kwargs):
        super().__init__()
        if feat_type not in feat_funcs:
            raise RuntimeError(
                f"Unknown feat_type: {feat_type}, must be one of "
                f"{list(feat_funcs)}")
        self.files = files
        self.labels = labels
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self.feat_config = kwargs
        self._extractors = {}  # keyed by sample rate: the mel filterbank and
        #                        jit trace are built once, not per item

    def _extractor(self, sample_rate):
        ex = self._extractors.get(sample_rate)
        if ex is None:
            feat_func = feat_funcs[self.feat_type]
            if self.feat_type != "spectrogram":
                ex = feat_func(sr=sample_rate, **self.feat_config)
            else:
                ex = feat_func(**self.feat_config)
            self._extractors[sample_rate] = ex
        return ex

    def _convert_to_record(self, idx):
        file, label = self.files[idx], self.labels[idx]
        waveform, sample_rate = backends.load(file)
        self.sample_rate = sample_rate
        v = waveform._value
        if v.ndim == 2:
            v = v[0]  # mono view, [T]
        from ..tensor import Tensor

        if feat_funcs[self.feat_type] is None:
            return Tensor(v), label
        x = Tensor(v[None, :])  # (batch, T)
        return self._extractor(sample_rate)(x).squeeze(0), label

    def __getitem__(self, idx):
        return self._convert_to_record(idx)

    def __len__(self):
        return len(self.files)


class ESC50(AudioClassificationDataset):
    """Reference datasets/esc50.py — 2000 5-second environmental recordings,
    50 classes, 5 predefined folds (meta/esc50.csv column `fold`); `split`
    selects the held-out fold."""

    label_list = [
        "Dog", "Rooster", "Pig", "Cow", "Frog", "Cat", "Hen",
        "Insects (flying)", "Sheep", "Crow",
        "Rain", "Sea waves", "Crackling fire", "Crickets", "Chirping birds",
        "Water drops", "Wind", "Pouring water", "Toilet flush", "Thunderstorm",
        "Crying baby", "Sneezing", "Clapping", "Breathing", "Coughing",
        "Footsteps", "Laughing", "Brushing teeth", "Snoring",
        "Drinking, sipping",
        "Door knock", "Mouse click", "Keyboard typing", "Door, wood creaks",
        "Can opening", "Washing machine", "Vacuum cleaner", "Clock alarm",
        "Clock tick", "Glass breaking",
        "Helicopter", "Chainsaw", "Siren", "Car horn", "Engine", "Train",
        "Church bells", "Airplane", "Fireworks", "Hand saw",
    ]
    meta = os.path.join("ESC-50-master", "meta", "esc50.csv")
    audio_path = os.path.join("ESC-50-master", "audio")

    def __init__(self, mode="train", split=1, feat_type="raw", data_home=None,
                 **kwargs):
        assert split in range(1, 6), f"1 <= split <= 5, got {split}"
        files, labels = self._get_data(mode, split, _data_home(data_home))
        super().__init__(files=files, labels=labels, feat_type=feat_type,
                         **kwargs)

    def _get_data(self, mode, split, home):
        meta_path = os.path.join(home, self.meta)
        if not os.path.isfile(meta_path):
            raise FileNotFoundError(
                f"{meta_path} not found — extract ESC-50-master.zip under "
                f"{home} (no network egress; download disabled)")
        files, labels = [], []
        with open(meta_path) as rf:
            for line in rf.readlines()[1:]:
                filename, fold, target = line.strip().split(",")[:3]
                keep = (int(fold) != split) if mode == "train" else (
                    int(fold) == split)
                if keep:
                    files.append(os.path.join(home, self.audio_path, filename))
                    labels.append(int(target))
        return files, labels


class TESS(AudioClassificationDataset):
    """Reference datasets/tess.py — 2800 emotional speech clips named
    <speaker>_<word>_<emotion>.wav; folds assigned round-robin by index."""

    label_list = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]
    audio_path = "TESS_Toronto_emotional_speech_set"

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 data_home=None, **kwargs):
        assert isinstance(n_folds, int) and n_folds >= 1, n_folds
        assert split in range(1, n_folds + 1), (split, n_folds)
        files, labels = self._get_data(mode, n_folds, split,
                                       _data_home(data_home))
        super().__init__(files=files, labels=labels, feat_type=feat_type,
                         **kwargs)

    def _get_data(self, mode, n_folds, split, home):
        root = os.path.join(home, self.audio_path)
        if not os.path.isdir(root):
            raise FileNotFoundError(
                f"{root} not found — extract the TESS archive under {home} "
                "(no network egress; download disabled)")
        wav_files = []
        for r, _, fs in sorted(os.walk(root)):
            for f in sorted(fs):
                if f.endswith(".wav"):
                    wav_files.append(os.path.join(r, f))
        files, labels = [], []
        for idx, path in enumerate(wav_files):
            emotion = os.path.basename(path)[:-4].split("_")[2]
            target = self.label_list.index(emotion)
            fold = idx % n_folds + 1
            keep = (fold != split) if mode == "train" else (fold == split)
            if keep:
                files.append(path)
                labels.append(target)
        return files, labels
