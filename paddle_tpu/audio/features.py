"""paddle.audio.features — Spectrogram / MelSpectrogram / LogMelSpectrogram / MFCC.

Reference: python/paddle/audio/features/layers.py. TPU path: framing is one
strided gather, the STFT is a single batched rfft (XLA FFT), mel projection is
a matmul on the MXU.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn.layer import Layer
from ..tensor import Tensor
from . import functional as AF


def _frame(x, frame_length, hop_length, center=True, pad_mode="reflect"):
    """x: [..., T] → [..., n_frames, frame_length]."""
    if center:
        pad = frame_length // 2
        cfg = [(0, 0)] * (x.ndim - 1) + [(pad, pad)]
        x = jnp.pad(x, cfg, mode=pad_mode)
    t = x.shape[-1]
    n_frames = 1 + (t - frame_length) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(frame_length)[None, :])
    return x[..., idx]


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = AF.get_window(window, self.win_length, dtype=dtype)._value
        if self.win_length < n_fft:  # center-pad window to n_fft
            lp = (n_fft - self.win_length) // 2
            w = jnp.pad(w, (lp, n_fft - self.win_length - lp))
        self._window = w

    def forward(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        frames = _frame(v.astype(jnp.float32), self.n_fft, self.hop_length,
                        self.center, self.pad_mode)
        spec = jnp.fft.rfft(frames * self._window, axis=-1)
        mag = jnp.abs(spec)
        if self.power != 1.0:
            mag = mag ** self.power
        # paddle layout: [..., freq, time]
        return Tensor(jnp.swapaxes(mag, -1, -2))


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                        power, center, pad_mode, dtype)
        self.n_mels = n_mels
        self._fbank = AF.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype)._value

    def forward(self, x):
        spec = self._spectrogram(x)._value  # [..., freq, time]
        mel = jnp.einsum("mf,...ft->...mt", self._fbank, spec)
        return Tensor(mel)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                   power, center, pad_mode, n_mels, f_min,
                                   f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._mel(x)
        return AF.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._log_mel = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center, pad_mode,
            n_mels, f_min, f_max, htk, norm, ref_value, amin, top_db, dtype)
        self._dct = AF.create_dct(n_mfcc, n_mels, dtype=dtype)._value

    def forward(self, x):
        logmel = self._log_mel(x)._value  # [..., n_mels, time]
        return Tensor(jnp.einsum("mk,...mt->...kt", self._dct, logmel))
