"""paddle.audio surface. Reference: python/paddle/audio/__init__.py."""
from . import functional  # noqa: F401
from . import features  # noqa: F401
from .features import MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram  # noqa: F401
