"""paddle.audio surface. Reference: python/paddle/audio/__init__.py
(__all__: backends, datasets, features, functional, info, load, save)."""
from . import functional  # noqa: F401
from . import features  # noqa: F401
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from .backends import info, load, save  # noqa: F401
from .features import MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram  # noqa: F401
