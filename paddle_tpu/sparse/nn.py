"""paddle.sparse.nn — layers over sparse COO tensors.

Reference: python/paddle/sparse/nn/ (ReLU/LeakyReLU/Softmax activations,
BatchNorm/SyncBatchNorm over sparse values, Conv3D/SubmConv3D point-cloud
convolutions; kernels in paddle/phi/kernels/sparse/, 113 files).

TPU-native shape: activations and BatchNorm act on the VALUES array only
(nnz-major — exactly the reference's sparse kernels' structure). The 3-D
convolutions run as gather-compute-scatter over the dense grid via XLA
(conv on the densified block): semantically identical to the reference's
rulebook kernels; a Pallas gather-matmul rulebook is the perf path for
large sparse grids and is future work (documented honestly)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..nn.layer import Layer
from ..nn import initializer as I
from ..tensor import Tensor
from . import SparseCooTensor

__all__ = ["ReLU", "LeakyReLU", "Softmax", "BatchNorm", "SyncBatchNorm",
           "Conv3D", "SubmConv3D", "MaxPool3D"]


def _map_values(sp: SparseCooTensor, fn) -> SparseCooTensor:
    bcoo = sp._bcoo
    return SparseCooTensor(
        jsparse.BCOO((fn(bcoo.data), bcoo.indices), shape=bcoo.shape))


class ReLU(Layer):
    """Reference: sparse/nn/layer/activation.py ReLU (values-only)."""

    def forward(self, x: SparseCooTensor):
        return _map_values(x, lambda v: jnp.maximum(v, 0))


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x: SparseCooTensor):
        a = self.negative_slope
        return _map_values(x, lambda v: jnp.where(v >= 0, v, a * v))


class Softmax(Layer):
    """Softmax over the last dense axis of the values (reference:
    sparse softmax over each row's stored entries for CSR; for COO with
    dense trailing dims this is the per-entry feature softmax)."""

    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x: SparseCooTensor):
        return _map_values(x, lambda v: jax.nn.softmax(v, axis=self.axis))


class BatchNorm(Layer):
    """BatchNorm over the channel (last) dim of the values.

    Reference: sparse/nn/layer/norm.py BatchNorm — statistics over all stored
    points, per channel."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 name=None):
        super().__init__()
        self._momentum = momentum
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean",
                             Tensor(jnp.zeros([num_features], jnp.float32)))
        self.register_buffer("_variance",
                             Tensor(jnp.ones([num_features], jnp.float32)))

    def forward(self, x: SparseCooTensor):
        from ..nn import functional as F

        vals = x._bcoo.data  # [nnz, C]
        out = F.batch_norm(
            Tensor(vals), self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format="NC" if vals.ndim == 2
            else "NCHW")
        return SparseCooTensor(
            jsparse.BCOO((out._value, x._bcoo.indices), shape=x._bcoo.shape))


class SyncBatchNorm(BatchNorm):
    """GSPMD makes the stats reductions cross-replica when the point axis is
    sharded — same identity as the dense SyncBatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class Conv3D(Layer):
    """Sparse 3-D convolution on NDHWC COO input.

    Reference: sparse/nn/layer/conv.py Conv3D (rulebook gather-scatter
    kernels). Here: densify -> XLA conv -> sparsify non-zeros, which is
    numerically identical; fine for moderate grids, memory-bound for huge
    ones (Pallas rulebook = future work)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__()
        if data_format != "NDHWC":
            raise ValueError("sparse Conv3D supports NDHWC only")
        ks = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(kernel_size)
        self._subm = subm
        self._stride = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
        self._padding = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
        self._dilation = (dilation,) * 3 if isinstance(dilation, int) else tuple(dilation)
        self._groups = groups
        # paddle sparse kernel layout: [kd, kh, kw, in/groups, out]
        self.weight = self.create_parameter(
            [*ks, in_channels // groups, out_channels], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = (self.create_parameter([out_channels], is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x: SparseCooTensor):
        dense = x._bcoo.todense()  # [N, D, H, W, C]
        w = self.weight._value  # [kd,kh,kw,ci,co]
        stride = self._stride
        if self._subm:
            # submanifold conv: output sites == input sites, stride 1, SAME pad
            stride = (1, 1, 1)
            pads = [(d * (k - 1) // 2, d * (k - 1) - d * (k - 1) // 2)
                    for k, d in zip(w.shape[:3], self._dilation)]
        else:
            pads = [(p, p) for p in self._padding]
        out = jax.lax.conv_general_dilated(
            dense.astype(w.dtype), w, window_strides=stride, padding=pads,
            rhs_dilation=self._dilation,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
            feature_group_count=self._groups)
        if self.bias is not None:
            out = out + self.bias._value
        if self._subm:
            # keep exactly the input's active sites (submanifold contract)
            mask = jnp.zeros(out.shape[:-1], bool).at[
                tuple(x._bcoo.indices[:, i] for i in range(4))].set(True)
            out = jnp.where(mask[..., None], out, 0)
            bcoo = jsparse.BCOO(
                (out[tuple(x._bcoo.indices[:, i] for i in range(4))],
                 x._bcoo.indices),
                shape=out.shape)
            return SparseCooTensor(bcoo)
        return SparseCooTensor(jsparse.BCOO.fromdense(out, n_batch=0,
                                                      n_dense=1))


class SubmConv3D(Conv3D):
    """Submanifold sparse conv (reference SubmConv3D): active sites are
    preserved — no dilation of the active set."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True,
                         padding_mode=padding_mode, weight_attr=weight_attr,
                         bias_attr=bias_attr, data_format=data_format)


class MaxPool3D(Layer):
    """Reference: sparse/nn/layer/pooling.py MaxPool3D (NDHWC)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        ks = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(kernel_size)
        st = stride or kernel_size
        self._ks = ks
        self._stride = (st,) * 3 if isinstance(st, int) else tuple(st)
        self._padding = (padding,) * 3 if isinstance(padding, int) else tuple(padding)

    def forward(self, x: SparseCooTensor):
        dense = x._bcoo.todense()
        neg = jnp.finfo(dense.dtype).min if jnp.issubdtype(
            dense.dtype, jnp.floating) else jnp.iinfo(dense.dtype).min
        out = jax.lax.reduce_window(
            dense, neg, jax.lax.max,
            window_dimensions=(1, *self._ks, 1),
            window_strides=(1, *self._stride, 1),
            padding=[(0, 0)] + [(p, p) for p in self._padding] + [(0, 0)])
        out = jnp.where(out == neg, 0, out)
        return SparseCooTensor(jsparse.BCOO.fromdense(out, n_batch=0,
                                                      n_dense=1))
