"""paddle.sparse — COO/CSR tensors and sparse ops.

Reference: python/paddle/sparse/__init__.py (sparse_coo_tensor,
sparse_csr_tensor, unary/binary ops, matmul). TPU-native backend:
jax.experimental.sparse.BCOO — XLA compiles its gather/scatter kernels, and
BCOO matmul lowers to segment-sum matmuls that run on the MXU. CSR is kept as
a view format (crows/cols/values) converting through COO, matching how the
reference treats CSR on non-CPU backends.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..tensor import Tensor


def _val(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor: indices [sparse_ndim, nnz] + values [nnz, ...]."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # --------------------------------------------------------------- properties
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(self._bcoo.indices.T)  # paddle layout [ndim, nnz]

    def values(self):
        return Tensor(self._bcoo.data)

    # --------------------------------------------------------------- conversions
    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self):
        dense_shape = self._bcoo.shape
        if len(dense_shape) != 2:
            raise ValueError("CSR requires a 2-D tensor")
        coo = self.coalesce()
        idx = np.asarray(coo._bcoo.indices)
        vals = coo._bcoo.data
        order = np.lexsort((idx[:, 1], idx[:, 0]))
        rows, cols = idx[order, 0], idx[order, 1]
        crows = np.zeros(dense_shape[0] + 1, dtype=np.int64)
        np.add.at(crows[1:], rows, 1)
        crows = np.cumsum(crows)
        return SparseCsrTensor(
            Tensor(jnp.asarray(crows)), Tensor(jnp.asarray(cols)),
            Tensor(vals[jnp.asarray(order)]), dense_shape)

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates())

    # --------------------------------------------------------------- ops
    def __add__(self, other):
        if isinstance(other, SparseCooTensor):
            return SparseCooTensor(
                jsparse.BCOO.fromdense(self._bcoo.todense() + other._bcoo.todense()))
        return Tensor(self._bcoo.todense() + _val(other))

    def __matmul__(self, other):
        return matmul(self, other)

    def transpose(self, perm=(1, 0)):
        return SparseCooTensor(self._bcoo.transpose(tuple(perm)))

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape):
        self._crows, self._cols, self._values = crows, cols, values
        self._shape = tuple(shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def nnz(self):
        return int(self._values.shape[0])

    def crows(self):
        return self._crows

    def cols(self):
        return self._cols

    def values(self):
        return self._values

    def to_sparse_coo(self, sparse_dim=2):
        crows = np.asarray(self._crows._value)
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
        idx = jnp.stack([jnp.asarray(rows), self._cols._value], axis=1)
        bcoo = jsparse.BCOO((self._values._value, idx), shape=self._shape)
        return SparseCooTensor(bcoo)

    def to_dense(self):
        return self.to_sparse_coo().to_dense()

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


# ------------------------------------------------------------------ constructors
def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """Reference: sparse/creation.py:sparse_coo_tensor. indices [ndim, nnz]."""
    idx = np.asarray(_val(indices)).T  # BCOO wants [nnz, ndim]
    vals = _val(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(0)) + tuple(vals.shape[1:])
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx)), shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    vals = _val(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    return SparseCsrTensor(Tensor(_val(crows)), Tensor(_val(cols)),
                           Tensor(vals), shape)


def to_sparse_coo(x, sparse_dim=None):
    return SparseCooTensor(jsparse.BCOO.fromdense(_val(x)))


# ------------------------------------------------------------------ functional
def matmul(a, b):
    """sparse @ dense (and sparse @ sparse via densify of b)."""
    if isinstance(a, SparseCooTensor):
        bv = b._bcoo.todense() if isinstance(b, SparseCooTensor) else _val(b)
        return Tensor(a._bcoo @ bv)
    if isinstance(a, SparseCsrTensor):
        return matmul(a.to_sparse_coo(), b)
    raise TypeError("matmul: first operand must be sparse")


def add(a, b):
    return a + b


def _unary(name, jfn, domain_preserving=True):
    def fn(x):
        if isinstance(x, SparseCooTensor):
            # zero-preserving unary ops act on stored values only
            b = x._bcoo
            return SparseCooTensor(jsparse.BCOO((jfn(b.data), b.indices),
                                                shape=b.shape))
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(x._crows, x._cols,
                                   Tensor(jfn(x._values._value)), x._shape)
        return Tensor(jfn(_val(x)))

    fn.__name__ = name
    return fn


relu = _unary("relu", lambda v: jnp.maximum(v, 0))
abs = _unary("abs", jnp.abs)
neg = _unary("neg", jnp.negative)
sin = _unary("sin", jnp.sin)
tanh = _unary("tanh", jnp.tanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
pow = None  # placeholder overwritten below


def pow(x, factor):  # noqa: F811
    if isinstance(x, SparseCooTensor):
        b = x._bcoo
        return SparseCooTensor(jsparse.BCOO((jnp.power(b.data, factor), b.indices),
                                            shape=b.shape))
    return Tensor(jnp.power(_val(x), factor))


def is_same_shape(a, b):
    return tuple(a.shape) == tuple(b.shape)

from . import nn  # noqa: E402,F401

# ------------------------------------------------------- round-5 parity tail
asin = _unary("asin", jnp.arcsin)
asinh = _unary("asinh", jnp.arcsinh)
atan = _unary("atan", jnp.arctan)
atanh = _unary("atanh", jnp.arctanh)
sinh = _unary("sinh", jnp.sinh)
tan = _unary("tan", jnp.tan)
expm1 = _unary("expm1", jnp.expm1)
log1p = _unary("log1p", jnp.log1p)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
isnan = _unary("isnan", jnp.isnan)


def cast(x, index_dtype=None, value_dtype=None):
    """Reference: sparse/unary.py cast — retype indices/values."""
    if isinstance(x, SparseCooTensor):
        b = x._bcoo
        data = b.data.astype(value_dtype) if value_dtype else b.data
        idx = b.indices.astype(index_dtype) if index_dtype else b.indices
        return SparseCooTensor(jsparse.BCOO((data, idx), shape=b.shape))
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(
            Tensor(x._crows._value.astype(index_dtype)) if index_dtype else x._crows,
            Tensor(x._cols._value.astype(index_dtype)) if index_dtype else x._cols,
            Tensor(x._values._value.astype(value_dtype)) if value_dtype else x._values,
            x._shape)
    raise TypeError("cast expects a sparse tensor")


def coalesce(x, name=None):
    """Reference: sparse/unary.py coalesce — merge duplicate indices."""
    return x.coalesce() if isinstance(x, SparseCooTensor) else x


def _binary_ew(name, jfn):
    """Elementwise sparse-sparse / sparse-dense via dense compute (BCOO
    elementwise union semantics), re-sparsified — correctness-first; the
    hot sparse path in this framework is BCOO matmul, not elementwise."""

    def fn(a, b):
        av = a.to_dense()._value if isinstance(a, (SparseCooTensor, SparseCsrTensor)) else _val(a)
        bv = b.to_dense()._value if isinstance(b, (SparseCooTensor, SparseCsrTensor)) else _val(b)
        out = jfn(av, bv)
        if isinstance(a, SparseCsrTensor) or isinstance(b, SparseCsrTensor):
            d = SparseCooTensor(jsparse.BCOO.fromdense(out))
            return d.to_sparse_csr()
        if isinstance(a, SparseCooTensor) or isinstance(b, SparseCooTensor):
            return SparseCooTensor(jsparse.BCOO.fromdense(out))
        return Tensor(out)

    fn.__name__ = name
    return fn


subtract = _binary_ew("subtract", jnp.subtract)
multiply = _binary_ew("multiply", jnp.multiply)
divide = _binary_ew("divide", jnp.divide)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    """Reference: sparse/unary.py sum — dense-valued reduction."""
    v = x.to_dense()._value if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else _val(x)
    out = jnp.sum(v, axis=axis, keepdims=keepdim)
    if dtype:
        out = out.astype(dtype)
    return Tensor(out)


def transpose(x, perm, name=None):
    if isinstance(x, SparseCooTensor):
        return x.transpose(perm)
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo().transpose(perm).to_sparse_csr()
    return Tensor(jnp.transpose(_val(x), perm))


def reshape(x, shape, name=None):
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        dense = x.to_dense()._value.reshape(shape)
        out = SparseCooTensor(jsparse.BCOO.fromdense(dense))
        return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out
    return Tensor(jnp.reshape(_val(x), shape))


import builtins as _builtins  # noqa: E402


def slice(x, axes, starts, ends, name=None):  # noqa: F811
    """Reference: sparse/unary.py slice."""
    v = x.to_dense()._value if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else _val(x)
    sl = [_builtins.slice(None)] * v.ndim
    for ax, st, en in zip(axes, starts, ends):
        sl[int(ax)] = _builtins.slice(int(st), int(en))
    out = v[tuple(sl)]
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        coo = SparseCooTensor(jsparse.BCOO.fromdense(out))
        return coo.to_sparse_csr() if isinstance(x, SparseCsrTensor) else coo
    return Tensor(out)


def mv(a, vec, name=None):
    """Reference: sparse/matmul.py mv — sparse matrix @ dense vector."""
    return matmul(a, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """Reference: sparse/matmul.py addmm — beta*input + alpha*(x @ y)."""
    prod = matmul(x, y)
    pv = prod.to_dense()._value if isinstance(prod, (SparseCooTensor, SparseCsrTensor)) else _val(prod)
    iv = input.to_dense()._value if isinstance(input, (SparseCooTensor, SparseCsrTensor)) else _val(input)
    return Tensor(beta * iv + alpha * pv)


def masked_matmul(x, y, mask, name=None):
    """Reference: sparse/matmul.py masked_matmul — (x @ y) sampled at mask's
    sparsity pattern (SDDMM)."""
    xv, yv = _val(x), _val(y)
    if isinstance(mask, SparseCsrTensor):
        coo = mask.to_sparse_coo()
        idx = coo._bcoo.indices
        rows, cols = idx[:, 0], idx[:, 1]
        vals = jnp.einsum("nd,nd->n", xv[rows], yv[:, cols].T)
        out = SparseCooTensor(jsparse.BCOO((vals, idx), shape=mask.shape))
        return out.to_sparse_csr()
    if isinstance(mask, SparseCooTensor):
        idx = mask._bcoo.indices
        rows, cols = idx[:, 0], idx[:, 1]
        vals = jnp.einsum("nd,nd->n", xv[rows], yv[:, cols].T)
        return SparseCooTensor(jsparse.BCOO((vals, idx), shape=mask.shape))
    raise TypeError("masked_matmul: mask must be sparse")


def mask_as(x, mask, name=None):
    """Reference: sparse/unary.py mask_as — take dense x's values at mask's
    pattern."""
    xv = _val(x)
    if isinstance(mask, SparseCooTensor):
        idx = mask._bcoo.indices
        vals = xv[tuple(idx[:, i] for i in range(idx.shape[1]))]
        return SparseCooTensor(jsparse.BCOO((vals, idx), shape=mask.shape))
    if isinstance(mask, SparseCsrTensor):
        return mask_as(x, mask.to_sparse_coo()).to_sparse_csr()
    raise TypeError("mask_as: mask must be sparse")


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Reference: sparse pca_lowrank — densify (low-rank PCA needs dense
    rotations anyway) and reuse linalg.pca_lowrank."""
    from ..ops.linalg import pca_lowrank as _dense_pca

    v = x.to_dense() if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x
    return _dense_pca(v, q=q, center=center, niter=niter)
