"""Random ops over the stateless key chain. Reference: python/paddle/tensor/random.py.

Each call pulls a fresh fold-in key from framework.random (reproducible after
paddle.seed); everything is jax.random so it shards/jits cleanly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as _dt
from ..framework import random as _rng
from ..tensor import Tensor

__all__ = [
    "uniform", "uniform_", "normal", "normal_", "standard_normal", "randn", "rand",
    "randint", "randint_like", "randperm", "multinomial", "bernoulli", "poisson",
    "exponential_", "binomial", "standard_gamma", "log_normal", "cauchy_", "geometric_",
]


def _key():
    return _rng.next_key()


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
    shape = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]
    k = jax.random.key(seed) if seed else _key()
    return Tensor(jax.random.uniform(k, shape, dtype=dtype, minval=min, maxval=max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x._value = jax.random.uniform(
        _key(), x._value.shape, dtype=x._value.dtype, minval=min, maxval=max
    )
    return x


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else jnp.asarray(mean, _dt.get_default_dtype())
        s = std._value if isinstance(std, Tensor) else jnp.asarray(std, _dt.get_default_dtype())
        out_shape = np.broadcast_shapes(m.shape, s.shape)
        z = jax.random.normal(_key(), out_shape, dtype=jnp.result_type(m, s))
        return Tensor(m + s * z)
    shape = [int(v) for v in (shape or [1])]
    z = jax.random.normal(_key(), shape, dtype=_dt.get_default_dtype())
    return Tensor(mean + std * z)


def normal_(x, mean=0.0, std=1.0, name=None):
    z = jax.random.normal(_key(), x._value.shape, dtype=x._value.dtype)
    x._value = mean + std * z
    return x


def standard_normal(shape, dtype=None, name=None):
    dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
    shape = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]
    return Tensor(jax.random.normal(_key(), shape, dtype=dtype))


def randn(shape, dtype=None, name=None):
    return standard_normal(shape, dtype)


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, min=0.0, max=1.0)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    dtype = _dt.convert_dtype(dtype)
    shape = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]
    return Tensor(jax.random.randint(_key(), shape, low, high, dtype=dtype))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dtype = _dt.convert_dtype(dtype) or x.dtype
    return Tensor(jax.random.randint(_key(), x._value.shape, low, high).astype(dtype))


def randperm(n, dtype="int64", name=None):
    dtype = _dt.convert_dtype(dtype)
    return Tensor(jax.random.permutation(_key(), n).astype(dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    def sample(v):
        if replacement:
            logits = jnp.log(jnp.maximum(v, 1e-30))
            return jax.random.categorical(_key(), logits, axis=-1, shape=v.shape[:-1] + (num_samples,))
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(_key(), v.shape, dtype=jnp.float32)
        scores = jnp.log(jnp.maximum(v.astype(jnp.float32), 1e-30)) + g
        _, idx = jax.lax.top_k(scores, num_samples)
        return idx

    out = sample(x._value)
    return Tensor(out.astype(_dt.int64))


def bernoulli(x, name=None):
    u = jax.random.uniform(_key(), x._value.shape, dtype=jnp.float32)
    return Tensor((u < x._value.astype(jnp.float32)).astype(x._value.dtype))


def poisson(x, name=None):
    return Tensor(jax.random.poisson(_key(), x._value).astype(x._value.dtype))


def binomial(count, prob, name=None):
    c = count._value if isinstance(count, Tensor) else jnp.asarray(count)
    p = prob._value if isinstance(prob, Tensor) else jnp.asarray(prob)
    out = jax.random.binomial(_key(), c.astype(jnp.float32), p.astype(jnp.float32))
    return Tensor(out.astype(_dt.int64))


def standard_gamma(x, name=None):
    return Tensor(jax.random.gamma(_key(), x._value))


def exponential_(x, lam=1.0, name=None):
    x._value = jax.random.exponential(_key(), x._value.shape, dtype=x._value.dtype) / lam
    return x


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    shape = [int(v) for v in (shape or [1])]
    z = jax.random.normal(_key(), shape, dtype=_dt.get_default_dtype())
    return Tensor(jnp.exp(mean + std * z))


def cauchy_(x, loc=0, scale=1, name=None):
    x._value = loc + scale * jax.random.cauchy(_key(), x._value.shape, dtype=x._value.dtype)
    return x


def geometric_(x, probs, name=None):
    p = probs._value if isinstance(probs, Tensor) else jnp.asarray(probs, x._value.dtype)
    u = jax.random.uniform(_key(), x._value.shape, dtype=jnp.float32)
    x._value = (jnp.ceil(jnp.log1p(-u) / jnp.log1p(-p))).astype(x._value.dtype)
    return x
