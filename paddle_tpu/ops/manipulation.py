"""Shape/layout manipulation ops. Reference: python/paddle/tensor/manipulation.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as _dt
from ..tensor import Tensor
from . import apply_op

__all__ = [
    "reshape", "reshape_", "flatten", "transpose", "moveaxis", "swapaxes", "squeeze",
    "squeeze_", "unsqueeze", "unsqueeze_", "concat", "stack", "split", "chunk", "unbind",
    "unstack", "tile", "expand", "expand_as", "broadcast_to", "broadcast_tensors", "flip",
    "rot90", "roll", "repeat_interleave", "cast", "slice", "strided_slice", "crop",
    "pad", "gather", "gather_nd", "scatter", "scatter_nd", "scatter_nd_add",
    "index_select", "index_sample", "index_add", "index_put", "masked_select",
    "masked_fill", "masked_scatter", "take_along_axis", "put_along_axis", "tensordot",
    "as_complex", "as_real", "view", "view_as", "tolist", "atleast_1d", "atleast_2d",
    "atleast_3d", "diagonal", "diag_embed", "flatten_", "shard_index", "unfold",
    "split_sections",
]


def _axes(axis, ndim):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(a % ndim if isinstance(a, int) else int(a) % ndim for a in axis)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return axis % ndim if ndim else axis


def reshape(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = [int(v) for v in np.asarray(shape._value)]
    else:
        shape = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]
    return apply_op(lambda v: jnp.reshape(v, shape), "reshape", x)


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._value = out._value
    x._grad_node = out._grad_node
    x._grad_index = out._grad_index
    x.stop_gradient = out.stop_gradient
    return x


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(v):
        nd = v.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = list(v.shape[:s]) + [-1] + list(v.shape[e + 1:])
        return v.reshape(new_shape)

    return apply_op(f, "flatten", x)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    out = flatten(x, start_axis, stop_axis)
    x._value, x._grad_node, x._grad_index = out._value, out._grad_node, out._grad_index
    x.stop_gradient = out.stop_gradient
    return x


def transpose(x, perm=None, name=None):
    def f(v):
        p = perm
        if p is None:
            p = list(range(v.ndim))[::-1]
        return jnp.transpose(v, p)

    return apply_op(f, "transpose", x)


def moveaxis(x, source, destination, name=None):
    return apply_op(lambda v: jnp.moveaxis(v, source, destination), "moveaxis", x)


def swapaxes(x, axis0, axis1, name=None):
    return apply_op(lambda v: jnp.swapaxes(v, axis0, axis1), "swapaxes", x)


def squeeze(x, axis=None, name=None):
    def f(v):
        ax = axis
        if ax is None:
            return jnp.squeeze(v)
        if isinstance(ax, int):
            ax = [ax]
        ax = tuple(a % v.ndim for a in ax if v.shape[a % v.ndim] == 1)
        return jnp.squeeze(v, axis=ax) if ax else v

    return apply_op(f, "squeeze", x)


def squeeze_(x, axis=None, name=None):
    out = squeeze(x, axis)
    x._value, x._grad_node, x._grad_index = out._value, out._grad_node, out._grad_index
    x.stop_gradient = out.stop_gradient
    return x


def unsqueeze(x, axis, name=None):
    def f(v):
        ax = axis
        if isinstance(ax, Tensor):
            ax = [int(a) for a in np.asarray(ax._value).reshape(-1)]
        if isinstance(ax, int):
            ax = [ax]
        out = v
        for a in sorted(a % (out.ndim + 1) for a in ax):
            out = jnp.expand_dims(out, a)
        return out

    return apply_op(f, "unsqueeze", x)


def unsqueeze_(x, axis, name=None):
    out = unsqueeze(x, axis)
    x._value, x._grad_node, x._grad_index = out._value, out._grad_node, out._grad_index
    x.stop_gradient = out.stop_gradient
    return x


def concat(x, axis=0, name=None):
    tensors = list(x)
    ax = axis.item() if isinstance(axis, Tensor) else axis
    return apply_op(lambda *vs: jnp.concatenate(vs, axis=int(ax)), "concat", *tensors)


def stack(x, axis=0, name=None):
    return apply_op(lambda *vs: jnp.stack(vs, axis=axis), "stack", *list(x))


def split(x, num_or_sections, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)

    def f(v):
        a = ax % v.ndim
        if isinstance(num_or_sections, int):
            return list(jnp.split(v, num_or_sections, axis=a))
        secs = [
            int(s.item()) if isinstance(s, Tensor) else int(s) for s in num_or_sections
        ]
        total = v.shape[a]
        known = sum(s for s in secs if s >= 0)
        secs = [s if s >= 0 else total - known for s in secs]
        idx = np.cumsum(secs)[:-1]
        return list(jnp.split(v, idx, axis=a))

    return apply_op(f, "split", x)


split_sections = split


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    n = x.shape[axis % x.ndim]

    def f(v):
        return [jnp.squeeze(s, axis % v.ndim) for s in jnp.split(v, n, axis % v.ndim)]

    return apply_op(f, "unbind", x)


unstack = unbind


def tile(x, repeat_times, name=None):
    if isinstance(repeat_times, Tensor):
        repeat_times = [int(v) for v in np.asarray(repeat_times._value)]
    repeat_times = [int(r.item()) if isinstance(r, Tensor) else int(r) for r in repeat_times]
    return apply_op(lambda v: jnp.tile(v, repeat_times), "tile", x)


def expand(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = [int(v) for v in np.asarray(shape._value)]
    shape = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]

    def f(v):
        tgt = list(shape)
        # -1 means keep dim
        vshape = (1,) * (len(tgt) - v.ndim) + v.shape
        tgt = [vs if t == -1 else t for t, vs in zip(tgt, vshape)]
        return jnp.broadcast_to(v.reshape(vshape), tgt)

    return apply_op(f, "expand", x)


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    shapes = [tuple(t.shape) for t in inputs]
    out_shape = np.broadcast_shapes(*shapes)
    return [expand(t, list(out_shape)) for t in inputs]


def flip(x, axis, name=None):
    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply_op(lambda v: jnp.flip(v, axis=tuple(ax)), "flip", x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op(lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), "rot90", x)


def roll(x, shifts, axis=None, name=None):
    return apply_op(lambda v: jnp.roll(v, shifts, axis=axis), "roll", x)


def repeat_interleave(x, repeats, axis=None, name=None):
    def f(v, r):
        return jnp.repeat(v, r, axis=axis)

    rep = repeats if isinstance(repeats, Tensor) else None
    if rep is not None:
        return apply_op(lambda v, r: jnp.repeat(v, r, axis=axis), "repeat_interleave", x, rep)
    return apply_op(lambda v: jnp.repeat(v, repeats, axis=axis), "repeat_interleave", x)


def cast(x, dtype):
    d = _dt.convert_dtype(dtype)

    def f(v):
        return v.astype(d)

    return apply_op(f, "cast", x)


import builtins as _builtins

builtins_slice = _builtins.slice


def slice(input, axes, starts, ends):
    def f(v):
        idx = [builtins_slice(None)] * v.ndim
        for ax, s, e in zip(axes, starts, ends):
            s = int(s.item()) if isinstance(s, Tensor) else int(s)
            e = int(e.item()) if isinstance(e, Tensor) else int(e)
            idx[ax] = builtins_slice(s, e)
        return v[tuple(idx)]

    return apply_op(f, "slice", input)


def strided_slice(x, axes, starts, ends, strides, name=None):
    def f(v):
        idx = [builtins_slice(None)] * v.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins_slice(int(s), int(e), int(st))
        return v[tuple(idx)]

    return apply_op(f, "strided_slice", x)


def crop(x, shape=None, offsets=None, name=None):
    shp = [int(s) for s in (shape or x.shape)]
    offs = [int(o) for o in (offsets or [0] * len(shp))]

    def f(v):
        idx = tuple(builtins_slice(o, o + s) for o, s in zip(offs, shp))
        return v[idx]

    return apply_op(f, "crop", x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """paddle.nn.functional.pad semantics: `pad` is per-dim [lo, hi] pairs; for 4D/5D with
    len(pad)==4/6 it pads spatial dims per data_format."""
    if isinstance(pad, Tensor):
        pad = [int(v) for v in np.asarray(pad._value)]
    pad = [int(p) for p in pad]

    def f(v):
        nd = v.ndim
        if len(pad) == 2 * nd:
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # spatial-only form, e.g. NCHW + [left,right,top,bottom]
            widths = [(0, 0)] * nd
            n_spatial = len(pad) // 2
            if data_format.endswith("C"):  # NHWC/NDHWC: spatial dims 1..nd-2
                spatial = list(range(1, 1 + n_spatial))
            else:  # NCHW/NCDHW: spatial dims 2..
                spatial = list(range(nd - n_spatial, nd))
            # paddle orders pad pairs from last spatial dim outward? It orders as
            # (dim_left...) per W,H,D i.e. reversed over spatial dims.
            for i, d in enumerate(reversed(spatial)):
                widths[d] = (pad[2 * i], pad[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, widths, mode="constant", constant_values=value)
        return jnp.pad(v, widths, mode=jmode)

    return apply_op(f, "pad", x)


def gather(x, index, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply_op(
        lambda v, i: jnp.take(v, i.astype(jnp.int32).reshape(-1) if i.ndim else i.astype(jnp.int32), axis=ax),
        "gather", x, index,
    )


def gather_nd(x, index, name=None):
    def f(v, idx):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        out = v[tuple(jnp.moveaxis(idx, -1, 0))] if k == v.ndim else v[
            tuple(jnp.moveaxis(idx, -1, 0))
        ]
        return out

    return apply_op(f, "gather_nd", x, index)


def scatter(x, index, updates, overwrite=True, name=None):
    def f(v, i, u):
        i = i.astype(jnp.int32).reshape(-1)
        if overwrite:
            return v.at[i].set(u)
        return v.at[i].set(0.0).at[i].add(u)

    return apply_op(f, "scatter", x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    def f(i, u):
        z = jnp.zeros(list(shape), u.dtype)
        return z.at[tuple(jnp.moveaxis(i.astype(jnp.int32), -1, 0))].add(u)

    return apply_op(f, "scatter_nd", index, updates)


def scatter_nd_add(x, index, updates, name=None):
    def f(v, i, u):
        return v.at[tuple(jnp.moveaxis(i.astype(jnp.int32), -1, 0))].add(u)

    return apply_op(f, "scatter_nd_add", x, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply_op(
        lambda v, i: jnp.take(v, i.astype(jnp.int32).reshape(-1), axis=axis),
        "index_select", x, index,
    )


def index_sample(x, index):
    def f(v, i):
        rows = jnp.arange(v.shape[0])[:, None]
        return v[rows, i.astype(jnp.int32)]

    return apply_op(f, "index_sample", x, index)


def index_add(x, index, axis, value, name=None):
    def f(v, i, u):
        idx = [builtins_slice(None)] * v.ndim
        i = i.astype(jnp.int32)
        moved = jnp.moveaxis(v, axis, 0)
        um = jnp.moveaxis(u, axis, 0)
        out = moved.at[i].add(um)
        return jnp.moveaxis(out, 0, axis)

    return apply_op(f, "index_add", x, index, value)


def index_put(x, indices, value, accumulate=False, name=None):
    def f(v, u, *idx):
        idx = tuple(i.astype(jnp.int32) if jnp.issubdtype(i.dtype, jnp.integer) else i for i in idx)
        if accumulate:
            return v.at[idx].add(u)
        return v.at[idx].set(u)

    return apply_op(f, "index_put", x, value, *indices)


def masked_select(x, mask, name=None):
    # Data-dependent output shape: executes on host (documented dynamic-shape boundary,
    # same as reference's dynamic kernels; under jit use masked_fill/where instead).
    v = np.asarray(x._value)
    m = np.asarray(mask._value if isinstance(mask, Tensor) else mask)
    return Tensor(jnp.asarray(v[np.broadcast_to(m, v.shape)]))


def masked_fill(x, mask, value, name=None):
    def f(v, m, val):
        val = jnp.asarray(val, v.dtype)
        return jnp.where(m, val, v)

    return apply_op(f, "masked_fill", x, mask, value if isinstance(value, Tensor) else None) \
        if isinstance(value, Tensor) else apply_op(
            lambda v, m: jnp.where(m, jnp.asarray(value, v.dtype), v), "masked_fill", x, mask)


def masked_scatter(x, mask, value, name=None):
    v = np.asarray(x._value).copy()
    m = np.broadcast_to(np.asarray(mask._value), v.shape)
    src = np.asarray(value._value).reshape(-1)
    v[m] = src[: int(m.sum())]
    return Tensor(jnp.asarray(v))


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply_op(
        lambda v, i: jnp.take_along_axis(v, i.astype(jnp.int32), axis=axis),
        "take_along_axis", arr, indices,
    )


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True, broadcast=True, name=None):
    def f(v, i, u):
        i = i.astype(jnp.int32)
        u = jnp.broadcast_to(jnp.asarray(u, v.dtype), i.shape)
        if reduce == "assign":
            return jnp.put_along_axis(v, i, u, axis=axis, inplace=False)
        if reduce in ("add", "sum"):
            dims = [jnp.arange(s) for s in i.shape]
            mesh = jnp.meshgrid(*dims, indexing="ij")
            full_idx = tuple(i if d == axis else mesh[d] for d in range(v.ndim))
            return v.at[full_idx].add(u)
        if reduce in ("mul", "multiply"):
            dims = [jnp.arange(s) for s in i.shape]
            mesh = jnp.meshgrid(*dims, indexing="ij")
            full_idx = tuple(i if d == axis else mesh[d] for d in range(v.ndim))
            return v.at[full_idx].multiply(u)
        raise ValueError(f"unsupported reduce {reduce}")

    val_t = values if isinstance(values, Tensor) else Tensor(jnp.asarray(values))
    return apply_op(f, "put_along_axis", arr, indices, val_t)


def tensordot(x, y, axes=2, name=None):
    def norm_axes(a):
        if isinstance(a, Tensor):
            a = np.asarray(a._value).tolist()
        if isinstance(a, (list, tuple)):
            return tuple(tuple(t) if isinstance(t, (list, tuple)) else t for t in a)
        return a

    return apply_op(lambda a, b: jnp.tensordot(a, b, axes=norm_axes(axes)), "tensordot", x, y)


def as_complex(x, name=None):
    return apply_op(lambda v: jax.lax.complex(v[..., 0], v[..., 1]), "as_complex", x)


def as_real(x, name=None):
    return apply_op(lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), "as_real", x)


def tolist(x):
    return x.tolist()


def atleast_1d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_1d, "atleast_1d", t) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_2d, "atleast_2d", t) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_3d, "atleast_3d", t) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(
        lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2), "diagonal", x
    )


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    def f(v):
        n = v.shape[-1] + abs(offset)
        base = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        r = idx if offset >= 0 else idx - offset
        c = idx + offset if offset >= 0 else idx
        out = base.at[..., r, c].set(v)
        # move the two new dims into place
        d1 = dim1 % out.ndim
        d2 = dim2 % out.ndim
        perm = [i for i in range(out.ndim) if i not in (out.ndim - 2, out.ndim - 1)]
        order = sorted([(d1, out.ndim - 2), (d2, out.ndim - 1)])
        for pos, src in order:
            perm.insert(pos, src)
        return jnp.transpose(out, perm)

    return apply_op(f, "diag_embed", input)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def f(v):
        shard_size = (index_num + nshards - 1) // nshards
        lo = shard_id * shard_size
        hi = lo + shard_size
        in_shard = (v >= lo) & (v < hi)
        return jnp.where(in_shard, v - lo, ignore_value)

    return apply_op(f, "shard_index", input)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (paddle.nn.functional.unfold). x: [N,C,H,W] → [N, C*kh*kw, L]."""
    kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) else kernel_sizes
    sh, sw = (strides, strides) if isinstance(strides, int) else strides
    dh, dw = (dilations, dilations) if isinstance(dilations, int) else dilations
    if isinstance(paddings, int):
        pt = pb = pl = pr = paddings
    elif len(paddings) == 2:
        pt = pb = paddings[0]
        pl = pr = paddings[1]
    else:
        pt, pl, pb, pr = paddings

    def f(v):
        n, c, h, w = v.shape
        vp = jnp.pad(v, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
        hp, wp = vp.shape[2], vp.shape[3]
        oh = (hp - (dh * (kh - 1) + 1)) // sh + 1
        ow = (wp - (dw * (kw - 1) + 1)) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            vp, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )  # [N, C*kh*kw, oh, ow]
        return patches.reshape(n, c * kh * kw, oh * ow)

    return apply_op(f, "unfold", x)
